"""File-backed genomics source: VCF / wire-JSONL variants, SAM reads.

The reference lived on real 1000 Genomes data served by the (since sunset)
Google Genomics API (``rdd/VariantsRDD.scala:198-225``); its only offline
ingest was resuming pre-materialized ``objectFile`` records
(``VariantsPca.scala:112-113``). This source makes local files a first-class
backend behind the same :class:`GenomicsSource` seam, so every pipeline
(the PCoA driver, the seven example analyses) runs unchanged on real data:

- ``*.vcf`` / ``*.vcf.gz`` — VCF 4.x text: sites, INFO (``AF`` feeds the
  ``--min-allele-frequency`` filter), and per-sample GT calls.
- ``*.jsonl`` / ``*.jsonl.gz`` — one wire-format variant dict per line (the
  REST SearchVariants item shape), or the checkpoint entry shape
  ``{"key": ..., "variant": ...}``; a checkpoint DIRECTORY
  (``pipeline/checkpoint.py``) is read via its part files.
- ``*.sam`` — SAM text alignments for the reads analyses.

Files parse once — through the shared windowed stream abstraction
(``sources/stream.py``: bounded windows, partial-record carry, budgeted
accumulators) — into per-contig start-sorted SPOOLED tables: the record
index is resident, the records live in a disk spool and decode lazily per
query. Shard queries (``search_variants`` with STRICT/OVERLAPS boundaries)
bisect into them, so the partitioner/window machinery drives this source
exactly as it drives the REST and synthetic backends, with peak host
memory O(index + window) — never O(file) — on every path (proven:
``graftcheck hostmem`` audits this module with zero findings and zero
declared-unbounded sites; ``check/hostmem.py:conf_host_peak_bytes``
charges the index, window, and packed-column terms in closed form).

Each file is one variant set (or read group set) whose id is the file's
sanitized stem — e.g. ``/data/chr17.vcf.gz`` → ``chr17`` — with callset ids
``<set>-<i>`` so ``emit_result``'s dataset split on ``-`` works
(``VariantsPca.scala:275``).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import re
import threading
import warnings
from collections import deque

import numpy as np
from typing import Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

from spark_examples_tpu.sharding.contig import (
    Contig,
    SexChromosomeFilter,
    filter_sex_chromosomes,
)
from spark_examples_tpu.sources.base import (
    GenomicsClient,
    GenomicsSource,
    ShardBoundary,
)
from spark_examples_tpu.sources.stream import (
    ChunkedArrayBuilder,
    SortednessProbe,
    SpooledRecordTable,
    UnsortedStreamError,
    iter_byte_windows,
    iter_text_lines,
    wire_rows_bound,
)

#: letter → wire operation (inverse of ``ReadBuilder.CIGAR_MATCH``,
#: ``models/read.py``; SAM column 6).
_CIGAR_OPS = {
    "M": "ALIGNMENT_MATCH",
    "H": "CLIP_HARD",
    "S": "CLIP_SOFT",
    "D": "DELETE",
    "I": "INSERT",
    "P": "PAD",
    "=": "SEQUENCE_MATCH",
    "X": "SEQUENCE_MISMATCH",
    "N": "SKIP",
}

_CIGAR_RE = re.compile(r"(\d+)([MIDNSHP=X])")


def file_set_id(path: str) -> str:
    """A file's variant/read-group set id: the stem, sanitized so callset ids
    ``<set>-<i>`` split unambiguously on the FIRST '-' (dashes and other
    separators become '_')."""
    stem = os.path.basename(path.rstrip("/"))
    for suffix in (".gz", ".vcf", ".jsonl", ".sam"):
        if stem.endswith(suffix):
            stem = stem[: -len(suffix)]
    sanitized = re.sub(r"[^A-Za-z0-9_.]", "_", stem)
    return sanitized or "file"


def file_set_ids(paths: Sequence[str]) -> List[str]:
    """Set ids for a list of input files, in order; duplicates get a numeric
    suffix so every file stays addressable."""
    ids: List[str] = []
    for path in paths:
        base = file_set_id(path)
        candidate, k = base, 1
        while candidate in ids:
            k += 1
            candidate = f"{base}{k}"
        ids.append(candidate)
    return ids


_AF_CHARSET = frozenset("0123456789eE+-.")


def af_float(value: Optional[str]) -> float:
    """The file paths' AF grammar, shared bit for bit by the native parser
    (``native/vcfparse.cpp``), the Python fallback, and the file-backed wire
    filter: trim ``' '``/``'\\t'``, then the value must be 1..63 chars drawn
    from ``[0-9eE+-.]`` and float()-parseable; anything else — including a
    missing value — behaves as absent (NaN, which compares False against any
    threshold). The charset gate closes every strtod↔float() divergence
    (hex forms, digit underscores, inf/nan words, exotic whitespace). The
    REST path keeps the reference's throwing ``float()``
    (``VariantsPca.scala:136-148`` ``.toDouble``).

    JSONL wire records may carry AF as a JSON number rather than a string
    (``{"info": {"AF": [0.25]}}``) — numbers pass straight through."""
    if value is None:
        return float("nan")
    if isinstance(value, (int, float)):
        return float(value)
    value = value.strip(" \t")
    if not value or len(value) >= 64 or not _AF_CHARSET.issuperset(value):
        return float("nan")
    try:
        return float(value)
    except ValueError:
        return float("nan")


def default_ingest_workers() -> int:
    """Default parse worker count for the chunk-parallel ingest engine:
    ``min(8, cpu_count)`` — past ~8 threads the native parser is host
    memory-bandwidth-bound, and tiny containers should not oversubscribe."""
    return max(1, min(8, os.cpu_count() or 1))


def _resolve_ingest_workers(ingest_workers: Optional[int]) -> int:
    """``None`` = auto (:func:`default_ingest_workers`), ``0`` = the serial
    oracle path, ``N >= 1`` = exactly N parse threads."""
    if ingest_workers is None:
        return default_ingest_workers()
    workers = int(ingest_workers)
    if workers < 0:
        raise ValueError(f"ingest workers must be >= 0, got {workers}")
    return workers


def _ordered_pool_map(fn, items, workers: int, window: Optional[int] = None):
    """Map ``fn`` over ``items`` on a thread pool, yielding results in INPUT
    order with a bounded in-flight window — the order-preserving merge of the
    chunk-parallel ingest engine.

    Backpressure is structural: at most ``window`` results exist at once
    (pending futures + the one being yielded), and the source iterator is
    only advanced when a slot frees, so a slow consumer bounds both the pool
    queue AND how far a streaming reader runs ahead. ``workers <= 1``
    degrades to the serial loop (the oracle path — no pool, no reordering
    risk, bitwise-identical by construction). Exceptions surface at the
    failed item's position in the output order.
    """
    if workers <= 1:
        for item in items:
            yield fn(item)
        return
    window = int(window or workers + 2)
    pending: deque = deque()
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=workers)
    try:
        for item in items:
            pending.append(pool.submit(fn, item))
            if len(pending) >= window:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()
    finally:
        for future in pending:
            future.cancel()
        pool.shutdown(wait=True)


def _line_aligned_spans(
    text: bytes, n_spans: int
) -> List[Tuple[int, int]]:
    """Split ``[0, len(text))`` into at most ``n_spans`` contiguous spans
    whose boundaries sit just past a ``'\\n'`` — the unit of work of the
    chunk-parallel parse. Concatenating the spans reproduces the buffer
    exactly; a final unterminated line stays whole in the last span."""
    size = len(text)
    if size == 0:
        return []
    n_spans = max(1, int(n_spans))
    target = -(-size // n_spans)
    spans: List[Tuple[int, int]] = []
    begin = 0
    while begin < size:
        cut = min(begin + target, size)
        if cut < size:
            nl = text.find(b"\n", cut - 1)
            cut = size if nl < 0 else nl + 1
        spans.append((begin, cut))
        begin = cut
    return spans


def _parse_vcf_info(text: str) -> Dict[str, List[str]]:
    """``AF=0.02,0.1;DB;NS=60`` → ``{"AF": ["0.02", "0.1"], "DB": [], ...}``."""
    info: Dict[str, List[str]] = {}
    if text in (".", ""):
        return info
    for item in text.split(";"):
        if "=" in item:
            key, value = item.split("=", 1)
            info[key] = value.split(",")
        elif item:
            info[item] = []
    return info


def _parse_genotype(gt: str) -> List[int]:
    """``0|1`` / ``0/1`` → ``[0, 1]``; missing alleles ('.') → -1 (the GA4GH
    convention; never counts as variation since only ``> 0`` does,
    ``VariantsPca.scala:67``)."""
    return [
        -1 if allele in (".", "") else int(allele)
        for allele in re.split(r"[/|]", gt)
    ]


def _vcf_line_record(
    line: str, path: str, set_id: str, samples: Sequence[str]
) -> Tuple[str, int, Dict]:
    """One VCF data line → ``(contig, start, wire record)`` — the single
    source of VCF data-line semantics, shared by the whole-file wire parser
    and the streaming chunk fallback so they cannot diverge.

    Wire-shape parity: VCF's 1-based POS becomes the half-open 0-based
    ``[start, end)`` interval the API used (``start = POS-1``,
    ``end = start + len(REF)``).
    """
    fields = line.split("\t")
    if len(fields) < 8:
        raise ValueError(
            f"{path}: malformed VCF data line (<8 fields): {line[:80]!r}"
        )
    chrom, pos, vid, ref, alt = fields[:5]
    start = int(pos) - 1
    record: Dict = {
        "referenceName": chrom,
        "variantSetId": set_id,
        "id": vid if vid != "." else f"{chrom}:{pos}:{ref}",
        "start": start,
        "end": start + len(ref),
        "referenceBases": ref,
        "info": _parse_vcf_info(fields[7]),
    }
    if vid != ".":
        record["names"] = vid.split(";")
    if alt not in (".", ""):
        record["alternateBases"] = alt.split(",")
    if len(fields) > 9 and samples:
        format_keys = fields[8].split(":")
        try:
            gt_index = format_keys.index("GT")
        except ValueError:
            gt_index = None
        calls = []
        for i, sample_field in enumerate(fields[9 : 9 + len(samples)]):
            call: Dict = {
                "callSetId": f"{set_id}-{i}",
                "callSetName": samples[i],
                "genotype": [],
            }
            if gt_index is not None:
                parts = sample_field.split(":")
                if gt_index < len(parts):
                    call["genotype"] = _parse_genotype(parts[gt_index])
            calls.append(call)
        record["calls"] = calls
    return chrom, start, record


def _parse_vcf(path: str, set_id: str, sink: SpooledRecordTable) -> List[Dict]:
    """Stream one VCF's data lines into ``sink`` (windowed read, one line
    resident at a time); → the callset list from the ``#CHROM`` header."""
    samples: List[str] = []
    for line in iter_text_lines(path):
        if not line:
            continue
        if line.startswith("#"):
            # '##' meta lines, the '#CHROM' column row, and any other
            # '#'-prefixed comment line are all header noise, never
            # data — matching the native parser (vcfparse.cpp skips
            # every '#' line), so the wire oracle and the packed paths
            # agree on comment-bearing files.
            if line.startswith("#CHROM"):
                columns = line.split("\t")
                samples = columns[9:] if len(columns) > 9 else []
            continue
        chrom, start, record = _vcf_line_record(line, path, set_id, samples)
        sink.add(chrom, start, record)
    return [
        {"id": f"{set_id}-{i}", "name": name} for i, name in enumerate(samples)
    ]


def _parse_jsonl(
    path: str, set_id: str, sink: SpooledRecordTable
) -> List[Dict]:
    """Stream wire-format JSON lines (bare variant dicts, or checkpoint
    entries ``{"key": ..., "variant": ...}``) into ``sink``. The cohort is
    taken from the first record carrying calls (1000G-style uniform
    cohorts)."""
    callsets: List[Dict] = []
    for line in iter_text_lines(path):
        line = line.strip()
        if not line:
            continue
        entry = json.loads(line)
        record = entry["variant"] if "variant" in entry else entry
        record = dict(record)
        record.setdefault("variantSetId", set_id)
        if not callsets and record.get("calls"):
            callsets = [
                {
                    "id": c.get("callSetId"),
                    "name": c.get("callSetName") or c.get("callSetId"),
                }
                for c in record["calls"]
            ]
        sink.add(record["referenceName"], int(record["start"]), record)
    return callsets


def _parse_sam(path: str, set_id: str, sink: SpooledRecordTable) -> List[Dict]:
    """Stream SAM text into ``sink`` as read wire dicts (the SearchReads
    item shape ``ReadBuilder.build`` consumes, ``models/read.py``)."""
    for line_no, line in enumerate(iter_text_lines(path)):
        if not line or line.startswith("@"):
            continue
        fields = line.split("\t")
        if len(fields) < 11:
            raise ValueError(
                f"{path}: malformed SAM data line (<11 fields): {line[:80]!r}"
            )
        qname, _flag, rname, pos, mapq, cigar, rnext, pnext, tlen, seq, qual = (
            fields[:11]
        )
        if rname == "*":
            continue  # unmapped: no position to shard on
        start = int(pos) - 1
        record: Dict = {
            "id": f"{set_id}:{line_no}",
            "fragmentName": qname,
            "readGroupSetId": set_id,
            "alignedSequence": "" if seq == "*" else seq,
            "fragmentLength": int(tlen),
            "alignment": {
                "position": {"referenceName": rname, "position": start},
                "mappingQuality": int(mapq),
                "cigar": [
                    {
                        "operationLength": int(length),
                        "operation": _CIGAR_OPS[op],
                    }
                    for length, op in _CIGAR_RE.findall(cigar)
                ],
            },
        }
        if qual != "*":
            record["alignedQuality"] = [ord(c) - 33 for c in qual]
        if rnext != "*":
            record["nextMatePosition"] = {
                "referenceName": rname if rnext == "=" else rnext,
                "position": int(pnext) - 1,
            }
        sink.add(rname, start, record)
    return []


def _load(path: str, set_id: str) -> Tuple[List[Dict], SpooledRecordTable, str]:
    """Parse one input into a finished spooled table. The table's row
    capacity is the closed-form wire bound (``stream.wire_rows_bound`` —
    the same number ``conf_host_peak_bytes`` charges), so the static proof
    is enforced live: an input violating it raises ``StreamBudgetError``
    instead of growing past the bound."""
    if os.path.isdir(path):
        # A checkpoint directory (``pipeline/checkpoint.py``): concatenation
        # of its part files. A directory with no part files is a wrong path
        # (e.g. the checkpoint's parent), not an empty cohort — fail loudly.
        parts = [n for n in sorted(os.listdir(path)) if n.startswith("part-")]
        if not parts:
            raise ValueError(
                f"{path!r} is a directory with no part-* files; expected a "
                "checkpoint directory written by save_variants "
                "(pipeline/checkpoint.py)"
            )
        cap = sum(wire_rows_bound(os.path.join(path, n)) for n in parts)
        sink = SpooledRecordTable(path, capacity_rows=cap)
        callsets: List[Dict] = []
        for name in parts:
            part_callsets = _parse_jsonl(os.path.join(path, name), set_id, sink)
            callsets = callsets or part_callsets
        return callsets, sink.finish(), "variants"
    sink = SpooledRecordTable(path, capacity_rows=wire_rows_bound(path))
    lowered = path[:-3] if path.endswith(".gz") else path
    if lowered.endswith(".vcf"):
        return _parse_vcf(path, set_id, sink), sink.finish(), "variants"
    if lowered.endswith(".jsonl"):
        return _parse_jsonl(path, set_id, sink), sink.finish(), "variants"
    if lowered.endswith(".sam"):
        return _parse_sam(path, set_id, sink), sink.finish(), "reads"
    raise ValueError(
        f"unsupported input file {path!r}: expected .vcf[.gz], .jsonl[.gz], "
        ".sam, or a checkpoint directory"
    )


class _FileTable:
    """One parsed file: per-contig start-sorted spooled records + bisect
    queries. Resident memory is the integer index; records decode lazily
    from the spool per query (``stream.SpooledRecordTable``)."""

    def __init__(self, path: str, set_id: str):
        self.path = path
        self.set_id = set_id
        self.callsets, self.table, self.kind = _load(path, set_id)

    def query(
        self, contig: str, start: int, end: int, boundary: ShardBoundary
    ) -> Iterator[Dict]:
        starts = self.table.starts(contig)
        if boundary is ShardBoundary.STRICT:
            # Exactly the records whose start lies in [start, end).
            lo = int(np.searchsorted(starts, start, side="left"))
            hi = int(np.searchsorted(starts, end - 1, side="right"))
            yield from self.table.iter_records(contig, lo, hi)
            return
        # OVERLAPS: any record intersecting [start, end). Starts are sorted
        # but ends are not, so scan the prefix with start < end and filter.
        hi = int(np.searchsorted(starts, end - 1, side="right"))
        for record in self.table.iter_records(contig, 0, hi):
            if _record_end(record) > start:
                yield record

    def contigs(self) -> List[Contig]:
        out: List[Contig] = []
        for name in sorted(self.table.contig_names()):
            starts = self.table.starts(name)
            last = int(starts[-1]) if len(starts) else 0
            span = _max_span(self.table.tail_records(name, 64))
            out.append(Contig(name, 0, last + span))
        return out


def _record_end(record: Dict) -> int:
    """Half-open end of a variant or read record. Reads derive theirs from
    the reference-consuming CIGAR operations (M/D/N/=/X), the SAM span."""
    alignment = record.get("alignment")
    if alignment is None:
        return int(record.get("end", int(record["start"]) + 1))
    position = int(alignment["position"]["position"])
    span = sum(
        int(unit["operationLength"])
        for unit in alignment.get("cigar", [])
        if unit["operation"]
        in ("ALIGNMENT_MATCH", "DELETE", "SKIP", "SEQUENCE_MATCH", "SEQUENCE_MISMATCH")
    )
    return position + max(1, span)


def _record_start(record: Dict) -> int:
    alignment = record.get("alignment")
    if alignment is None:
        return int(record["start"])
    return int(alignment["position"]["position"])


def _max_span(records: List[Dict]) -> int:
    """Upper-bound span of the LAST few records (for a contig's bound)."""
    return max(
        (max(1, _record_end(r) - _record_start(r)) for r in records[-64:]),
        default=1,
    )


#: SearchVariants page size mirrored by the packed path's request
#: accounting (one request per page per shard, at least one per shard) —
#: keeps I/O stats identical between the wire and packed ingest paths.
FILE_PAGE_SIZE = 1024


def _records_to_arrays(items, n_samples: int):
    """(contig, start, wire record) triples → the native parser's array
    tuple — THE one Python record→arrays conversion (AF grammar,
    has-variation rows, zero-fill of short sample rows), shared by the
    whole-file fallback and the streamed chunk fallback so the two cannot
    drift."""
    contigs: List[str] = []
    positions: List[int] = []
    ends: List[int] = []
    af: List[float] = []
    hv_rows: List[np.ndarray] = []
    for contig, start, record in items:
        contigs.append(contig)
        positions.append(start)
        ends.append(int(record["end"]))
        af_values = record.get("info", {}).get("AF")
        af.append(af_float(af_values[0] if af_values else None))
        row = np.zeros(n_samples, dtype=np.int8)
        for i, call in enumerate(record.get("calls", [])[:n_samples]):
            if any(g > 0 for g in call.get("genotype", [])):
                row[i] = 1
        hv_rows.append(row)
    hv = (
        np.stack(hv_rows)
        if hv_rows
        else np.zeros((0, n_samples), dtype=np.int8)
    )
    return (
        np.array(contigs, dtype=object),
        np.array(positions, dtype=np.int64),
        np.array(ends, dtype=np.int64),
        np.array(af, dtype=np.float64),
        hv,
    )


def _python_vcf_arrays(path: str, set_id: str):
    """Pure-Python fallback producing the same arrays as the native parser
    (``utils/native.py:parse_vcf_arrays``), derived from the wire records —
    staged through a spooled table so even the fallback oracle never holds
    the record set in memory. Like the native parser, rows with fewer
    sample columns than the header zero-fill the missing samples (the
    header is the cohort authority)."""
    sink = SpooledRecordTable(path, capacity_rows=wire_rows_bound(path))
    callsets = _parse_vcf(path, set_id, sink)
    table = sink.finish()
    return _records_to_arrays(
        (
            (contig, int(start), record)
            for contig in sorted(table.contig_names())
            for start, record in zip(
                table.starts(contig).tolist(), table.iter_records(contig)
            )
        ),
        len(callsets),
    )


def _native_parallel_vcf_arrays(text: bytes, workers: int):
    """Span-parallel native parse of one in-memory VCF buffer: split into
    line-aligned spans, parse spans concurrently through the GIL-releasing
    C-ABI parser (``utils/native.py:parse_vcf_span``), and reassemble the
    per-span arrays in file order. Byte-identical to the serial
    ``parse_vcf_arrays`` by construction: the cohort comes from the same
    whole-buffer ``vcf_scan``, every span runs the same per-line core, and
    concatenation in span order IS file order. ``None`` when the native
    library is unavailable.

    Since the packed path moved to windowed staging
    (``_chunked_vcf_arrays``), no production path holds a whole-file
    buffer to hand here — this is the span-level parity oracle the fuzz
    corpus drives (parallel == serial on every document, including the
    malformed-ordinal contract), kept as the reference implementation for
    any buffer-holding caller."""
    from spark_examples_tpu.utils.native import (
        parse_vcf_span,
        scan_vcf_counts,
    )

    from spark_examples_tpu.utils.native import MalformedVcfLine

    counts = scan_vcf_counts(text)
    if counts is None:
        return None
    _, n_samples = counts
    # More spans than workers so a comment/header-dense span cannot straggle
    # the whole pool; spans stay multi-MB for real inputs.
    spans = _line_aligned_spans(text, workers * 4)
    if not spans:
        from spark_examples_tpu.utils.native import parse_vcf_arrays

        return parse_vcf_arrays(text)
    parts = []
    rows_before = 0
    try:
        for arrays in _ordered_pool_map(
            lambda span: parse_vcf_span(text, span[0], span[1], n_samples),
            spans,
            workers,
        ):
            if arrays is None:  # library vanished mid-flight
                return None
            parts.append(arrays)
            rows_before += len(arrays[1])
    except MalformedVcfLine as e:
        # Results merge in span order, so every span BEFORE the failing one
        # has already been counted — the span-relative ordinal translates
        # to the file-level data-line number the serial parse reports.
        raise MalformedVcfLine(rows_before + e.ordinal) from None
    return tuple(
        np.concatenate([part[i] for part in parts]) for i in range(5)
    )


def _chunked_vcf_arrays(
    path: str, set_id: str, ingest_workers: Optional[int]
):
    """Windowed staging for the packed view: the streaming chunk engine
    (``_StreamedVcf.iter_chunk_arrays`` — bounded windows, partial-line
    carry, chunk-parallel native decode) feeds budgeted column builders
    (``stream.ChunkedArrayBuilder``, capacity = the closed-form wire row
    bound), replacing the retired whole-file buffer read. Peak staging is
    O(workers × chunk) for the parse plus the growing packed columns —
    both charged by ``conf_host_peak_bytes``'s packed term — and for
    ``.gz`` inputs the compressed stream decodes window by window, never
    resident beside more than one decompressed window.

    → ``((contigs, positions, ends, af, hv), native)``; byte-identical to
    the retired whole-buffer parse (concatenating line-aligned windows in
    file order IS file order — the parity the streaming tests pin)."""
    from spark_examples_tpu.utils.native import MalformedVcfLine

    view = _StreamedVcf(
        path,
        set_id,
        chunk_bytes=STREAM_CHUNK_BYTES,
        ingest_workers=ingest_workers,
    )
    cap = wire_rows_bound(path)
    n_samples = view.num_samples
    builders = (
        ChunkedArrayBuilder(object, capacity_rows=cap, label=path),
        ChunkedArrayBuilder(np.int64, capacity_rows=cap, label=path),
        ChunkedArrayBuilder(np.int64, capacity_rows=cap, label=path),
        ChunkedArrayBuilder(np.float64, capacity_rows=cap, label=path),
        ChunkedArrayBuilder(
            np.int8, row_shape=(n_samples,), capacity_rows=cap, label=path
        ),
    )
    rows_staged = 0
    try:
        for parts in view.iter_chunk_arrays():
            for builder, part in zip(builders, parts):
                builder.add(part)
            rows_staged += len(parts[1])
    except MalformedVcfLine as e:
        # Chunks merge in file order, so every chunk BEFORE the failing
        # one has been staged — the chunk-relative ordinal translates to
        # the file-level data-line number the serial parse reports.
        raise MalformedVcfLine(rows_staged + e.ordinal) from None
    return tuple(b.finish() for b in builders), view.native_decode


class _PackedVcf:
    """Column-oriented view of one VCF: per-contig start-sorted arrays
    (positions, AF, has-variation rows) feeding the packed ingest path —
    staged through the windowed chunk engine (native C++ decode when
    available, ``native/vcfparse.cpp``, chunk-parallel across
    ``ingest_workers`` threads; the shared-semantics Python fallback
    otherwise) with identical output (tested)."""

    def __init__(
        self,
        path: str,
        set_id: str,
        ingest_workers: Optional[int] = None,
    ):
        from spark_examples_tpu.utils.native import vcf_library

        self.path = path
        self.native = False
        _resolve_ingest_workers(ingest_workers)
        lowered = path[:-3] if path.endswith(".gz") else path
        if not lowered.endswith(".vcf"):
            raise ValueError(
                f"packed ingest needs a .vcf[.gz] input; got {path!r}"
            )
        # Probe library availability BEFORE reading: without a compiler the
        # chunk engine would pay the windowed read only to fall back per
        # chunk — the spooled Python oracle is the honest path there.
        if vcf_library() is not None:
            arrays, self.native = _chunked_vcf_arrays(
                path, set_id, ingest_workers
            )
        else:
            arrays = _python_vcf_arrays(path, set_id)
        contigs, positions, ends, af, hv = arrays
        self.num_samples = hv.shape[1]
        self.by_contig: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        self.contig_bounds: Dict[str, int] = {}
        for name in dict.fromkeys(contigs.tolist()):  # first-seen order
            mask = contigs == name
            order = np.argsort(positions[mask], kind="stable")
            self.by_contig[str(name)] = (
                positions[mask][order],
                af[mask][order],
                np.ascontiguousarray(hv[mask][order]),
            )
            self.contig_bounds[str(name)] = int(ends[mask].max())

    def window(self, contig: Contig):
        """(positions, af, hv) rows with start in [contig.start, contig.end)
        — the STRICT shard semantics of the wire path."""
        starts, af, hv = self.by_contig.get(
            contig.reference_name, (np.empty(0, np.int64), None, None)
        )
        if af is None:
            return (
                np.empty(0, np.int64),
                np.empty(0, np.float64),
                np.zeros((0, self.num_samples), np.int8),
            )
        lo = int(np.searchsorted(starts, contig.start, side="left"))
        hi = int(np.searchsorted(starts, contig.end - 1, side="right"))
        return starts[lo:hi], af[lo:hi], hv[lo:hi]


#: Decompressed bytes per streamed parse chunk (default; ``_StreamedVcf``).
STREAM_CHUNK_BYTES = 32 << 20

#: DECOMPRESSED bytes above which a VCF streams by default when no explicit
#: ``--stream-chunk-bytes`` is given. The reference's paging architecture
#: held one page per executor (``rdd/VariantsRDD.scala:198-225``);
#: whole-file parsing only wins below this scale.
STREAM_THRESHOLD_BYTES = 128 << 20

#: Conservative gzip ratio for VCF text (GT matrices compress 10-30×): the
#: auto-streaming decision compares a ``.gz`` file's on-disk size × this
#: against the decompressed threshold, so the standard compressed 1000
#: Genomes distribution streams instead of silently expanding to multi-GB
#: host arrays under the raw-size test.
_GZ_RATIO_ESTIMATE = 10


def _read_vcf_header_samples(path: str) -> List[str]:
    """Sample names from the ``#CHROM`` header row alone — O(header) work
    and memory, so callset discovery never pays a data parse. A headerless
    VCF (a data line before any ``#CHROM`` row) yields the empty cohort,
    exactly like the whole-file wire parser (``_parse_vcf``) — header-only
    discovery must not reject files the data parse would accept."""
    # A small window: the scan usually ends within the first KBs, and the
    # streamed-ingest memory tests pin the whole pass to O(chunk).
    for line in iter_text_lines(path, window_bytes=64 << 10):
        if not line:
            continue
        if line.startswith("#CHROM"):
            columns = line.split("\t")
            return columns[9:] if len(columns) > 9 else []
        if line.startswith("#"):
            # Any other '#'-prefixed line ('##' meta or a bare comment)
            # is header noise, not data: keep scanning for #CHROM. A
            # single-'#' comment before #CHROM previously ended the
            # scan here and silently yielded a 0-sample cohort.
            continue
        break  # a data line before #CHROM: headerless, no cohort
    return []


def _iter_vcf_chunks(path: str, chunk_bytes: int) -> Iterator[bytes]:
    """Stream a (possibly gzipped) text file in ~``chunk_bytes`` pieces that
    end at line boundaries (the partial last line carries into the next
    chunk), holding one chunk in memory at a time — the shared windowed
    reader (``sources/stream.py:iter_byte_windows``; the ``files.read``
    fault boundary and the 64-byte window floor live there)."""
    return iter_byte_windows(path, chunk_bytes, fault_label="files.read")


def _python_chunk_arrays(chunk: bytes, path: str, set_id: str, samples):
    """Pure-Python fallback for one streamed chunk: the same array tuple as
    ``utils/native.py:parse_vcf_chunk``, in FILE order, built through the
    shared per-line wire parser (``_vcf_line_record``) and the shared
    record→arrays conversion (``_records_to_arrays``) so streamed semantics
    cannot drift from the wire oracle at either layer."""
    return _records_to_arrays(
        (
            _vcf_line_record(line, path, set_id, samples)
            for line in chunk.decode("utf-8").splitlines()
            if line and not line.startswith("#")
        ),
        len(samples),
    )


def _contig_runs(contigs: np.ndarray) -> Iterator[Tuple[str, slice]]:
    """Maximal same-contig runs of a per-row contig array, in order."""
    if len(contigs) == 0:
        return
    changes = np.flatnonzero(contigs[1:] != contigs[:-1]) + 1
    edges = [0, *changes.tolist(), len(contigs)]
    for lo, hi in zip(edges[:-1], edges[1:]):
        yield str(contigs[lo]), slice(lo, hi)


class UnsortedVcfError(UnsortedStreamError):
    """A streaming pass met records out of coordinate order. Explicitly
    requested streaming (``--stream-chunk-bytes N``) surfaces this as the
    hard error it is; AUTO-selected streaming catches it and falls back to
    the in-memory path with a warning (``FileGenomicsSource``) — the
    size heuristic must not turn a file that loaded fine before the
    threshold existed into a hard failure."""


class _RunOrderCheck(SortednessProbe):
    """Coordinate-sortedness guard for one streaming pass — the VCF face
    of the shared ``stream.SortednessProbe`` contract (contig-contiguous,
    non-decreasing positions), raising :class:`UnsortedVcfError` with the
    VCF-specific remedy."""

    def __init__(self, path: str):
        super().__init__(
            path,
            error_cls=UnsortedVcfError,
            hint=(
                "streaming ingest needs a coordinate-sorted VCF; sort the "
                "input or disable streaming (--stream-chunk-bytes 0)"
            ),
        )


class StreamCounters:
    """I/O-stats accounting filled during one streaming pass, mirroring the
    in-memory packed path's numbers exactly: ``requests`` are pages per
    shard over PRE-filter rows (at least one per shard, empty included),
    ``variants`` are post-filter kept rows.

    ``registry`` (the run's metrics registry, optional) gets live progress
    gauges as the pass advances — ``ingest_sites_scanned`` (rows attributed
    to shard windows so far) and ``ingest_partitions_done`` (windows the
    file-order cursor has reached) — because the driver flushes these
    counters into its I/O stats only AFTER the stream is fully consumed;
    without the gauges a multi-hour streaming ingest would heartbeat 0/N
    the whole way.
    """

    def __init__(
        self,
        num_shards: int,
        page_size: int = FILE_PAGE_SIZE,
        registry=None,
    ):
        self.num_shards = int(num_shards)
        self.page_size = int(page_size)
        self.shard_rows: Dict[int, int] = {}
        self.variants = 0
        self._rows_seen = 0
        self._reached: set = set()
        self._sites_gauge = self._done_gauge = None
        if registry is not None:
            from spark_examples_tpu.obs.metrics import (
                INGEST_PARTITIONS_DONE,
                INGEST_SITES_SCANNED,
                well_known_gauge,
            )

            self._sites_gauge = well_known_gauge(
                registry, INGEST_SITES_SCANNED
            )
            self._done_gauge = well_known_gauge(
                registry, INGEST_PARTITIONS_DONE
            )

    def mark_window_reached(self, shard_index: int) -> None:
        """The file-order cursor reached this window — counted whether or
        not any record fell inside it, so the heartbeat's done/planned
        progress converges even with empty shard windows."""
        self._reached.add(shard_index)
        if self._done_gauge is not None:
            self._done_gauge.set(len(self._reached))

    def add_shard_rows(self, shard_index: int, n: int) -> None:
        """Pre-filter rows attributed to one shard window (page accounting
        derives from these in :meth:`requests`)."""
        self.shard_rows[shard_index] = self.shard_rows.get(shard_index, 0) + n
        self._rows_seen += n
        if self._sites_gauge is not None:
            self._sites_gauge.set(self._rows_seen)
        self.mark_window_reached(shard_index)

    def add_variants(self, n: int) -> None:
        """Post-filter kept rows."""
        self.variants += n

    def requests(self) -> int:
        nonempty = sum(
            -(-rows // self.page_size)
            for rows in self.shard_rows.values()
            if rows
        )
        empty = self.num_shards - sum(
            1 for rows in self.shard_rows.values() if rows
        )
        return nonempty + empty


class _StreamedVcf:
    """Bounded-memory streaming view of one VCF: one pass over the file in
    ``chunk_bytes`` pieces, native chunk parser when available
    (``native/vcfparse.cpp:vcf_parse`` is header-agnostic; the host carries
    partial lines), the shared-semantics Python fallback otherwise.

    This is the capability the reference's Spark ingest had by construction
    — one page in memory per executor (``rdd/VariantsRDD.scala:198-225``) —
    restated for the packed TPU ingest: peak host memory is O(chunk), not
    O(file), so real larger-than-RAM cohort ingests run end to end. Requires
    a coordinate-sorted VCF (checked; the in-memory view has no such
    requirement). Gramian accumulation commutes, so blocks stream in FILE
    order regardless of the requested shard order.
    """

    def __init__(
        self,
        path: str,
        set_id: str,
        chunk_bytes: int = STREAM_CHUNK_BYTES,
        ingest_workers: Optional[int] = None,
    ):
        self.path = path
        self.set_id = set_id
        self.chunk_bytes = int(chunk_bytes)
        self.ingest_workers = _resolve_ingest_workers(ingest_workers)
        self.samples = _read_vcf_header_samples(path)
        self.num_samples = len(self.samples)
        self.callsets = [
            {"id": f"{set_id}-{i}", "name": name}
            for i, name in enumerate(self.samples)
        ]
        self._bounds: Optional[Dict[str, int]] = None
        #: Whether the LAST ``iter_chunk_arrays`` pass decoded natively
        #: end to end (the packed view's ``native`` flag derives from it).
        self.native_decode = False

    def iter_chunk_arrays(self):
        """→ ``(contigs, positions, ends, af, hv)`` per chunk, file order.

        With ``ingest_workers >= 2`` and the native library available,
        chunks decode CONCURRENTLY on a thread pool (the C-ABI parse
        releases the GIL) while this generator yields them in file order —
        the streaming face of the chunk-parallel ingest engine. The
        in-flight window is bounded (``_ordered_pool_map``), so peak host
        memory grows from O(chunk) to O(workers × chunk), still independent
        of file size, and a slow consumer backpressures the reader. The
        pure-Python fallback stays serial: it holds the GIL, so a pool
        would only add overhead around the same single-core parse."""
        from spark_examples_tpu.utils.native import (
            parse_vcf_chunk,
            vcf_library,
        )

        self.native_decode = vcf_library() is not None

        def decode(chunk: bytes):
            arrays = parse_vcf_chunk(chunk, self.num_samples)
            if arrays is None:
                self.native_decode = False  # library vanished mid-flight
                arrays = _python_chunk_arrays(
                    chunk, self.path, self.set_id, self.samples
                )
            return arrays

        workers = self.ingest_workers if vcf_library() is not None else 0
        chunks = _iter_vcf_chunks(self.path, self.chunk_bytes)
        for arrays in _ordered_pool_map(decode, chunks, workers):
            if len(arrays[1]):
                yield arrays

    def contig_bounds(self) -> Dict[str, int]:
        """{contig: max record end} from a site-only streaming pass — lazy
        contig discovery for ``--all-references`` without the per-sample
        genotype walk (the result matches ``_PackedVcf.contig_bounds``)."""
        if self._bounds is None:
            from spark_examples_tpu.utils.native import scan_vcf_sites_chunk

            bounds: Dict[str, int] = {}
            order = _RunOrderCheck(self.path)
            for chunk in _iter_vcf_chunks(self.path, self.chunk_bytes):
                scanned = scan_vcf_sites_chunk(chunk)
                if scanned is None:
                    # Site-only on the fallback too: an empty sample list
                    # skips the per-sample genotype walk entirely
                    # (contig/position/end are sample-independent).
                    contigs, positions, ends = _python_chunk_arrays(
                        chunk, self.path, self.set_id, []
                    )[:3]
                else:
                    contigs, positions, ends = scanned
                for name, run in _contig_runs(contigs):
                    order.check(name, positions[run])
                    run_max = int(ends[run].max())
                    if run_max > bounds.get(name, 0):
                        bounds[name] = run_max
            self._bounds = bounds
        return self._bounds

    def stream_blocks(
        self,
        shards: Sequence[Contig],
        block_size: int = 1024,
        min_allele_frequency: Optional[float] = None,
        counters: Optional[StreamCounters] = None,
    ) -> Iterator[Dict]:
        """ONE streaming pass serving every shard window: yields the same
        block dicts as ``FileGenomicsSource.genotype_blocks`` (AF-filtered,
        all-zero-variation rows dropped), in file order. ``counters`` (when
        given) accumulates the wire-parity request/variant accounting the
        per-shard path computes from its random-access view."""
        by_name: Dict[str, List[Tuple[int, int, int]]] = {}
        for idx, shard in enumerate(shards):
            by_name.setdefault(shard.reference_name, []).append(
                (shard.start, shard.end, idx)
            )
        for lst in by_name.values():
            lst.sort()
        # Advancing per-contig cursor over the start-sorted shard list: runs
        # arrive in position order (checked), so shards wholly before the
        # current run never revive.
        cursor = {name: 0 for name in by_name}
        order = _RunOrderCheck(self.path)

        for contigs, positions, ends, af, hv in self.iter_chunk_arrays():
            for name, run in _contig_runs(contigs):
                pos = positions[run]
                order.check(name, pos)
                lst = by_name.get(name)
                if not lst:
                    continue
                run_lo, run_hi = int(pos[0]), int(pos[-1])
                p = cursor[name]
                while p < len(lst) and lst[p][1] <= run_lo:
                    # Window wholly behind the stream — reached (possibly
                    # empty), never revived.
                    if counters is not None:
                        counters.mark_window_reached(lst[p][2])
                    p += 1
                cursor[name] = p
                af_run = af[run]
                hv_run = hv[run]
                for start, end, idx in lst[p:]:
                    if start > run_hi:
                        break
                    if counters is not None:
                        counters.mark_window_reached(idx)
                    lo = int(np.searchsorted(pos, start, side="left"))
                    hi = int(np.searchsorted(pos, end, side="left"))
                    if hi <= lo:
                        continue
                    if counters is not None:
                        counters.add_shard_rows(idx, hi - lo)
                    s_pos, s_af, s_hv = pos[lo:hi], af_run[lo:hi], hv_run[lo:hi]
                    if min_allele_frequency is not None:
                        # The reference's rule (``VariantsPca.scala:
                        # 136-148``): strictly greater, first AF value,
                        # absent AF (NaN) never passes.
                        keep = s_af > min_allele_frequency
                        s_pos, s_af, s_hv = s_pos[keep], s_af[keep], s_hv[keep]
                    for off in range(0, len(s_pos), block_size):
                        hv_block = s_hv[off : off + block_size]
                        nonzero = hv_block.any(axis=1)
                        if not nonzero.any():
                            continue
                        if counters is not None:
                            counters.add_variants(int(nonzero.sum()))
                        yield {
                            "positions": s_pos[off : off + block_size][nonzero],
                            "has_variation": hv_block[nonzero].astype(np.uint8),
                            "af": s_af[off : off + block_size][nonzero],
                        }


class FileClient(GenomicsClient):
    """A per-partition session over the shared parsed tables; counts one
    initialized request per page of results (REST-parity accounting)."""

    def __init__(self, tables: Mapping[str, _FileTable]):
        super().__init__()
        self._tables = tables

    def _search(
        self, set_ids: Sequence[str], request: Mapping, boundary, page_size: int
    ) -> Iterator[Dict]:
        contig = request["referenceName"]
        start = int(request.get("start", 0))
        end = int(request.get("end", 1 << 62))
        emitted = 0
        for set_id in set_ids:
            table = self._tables.get(set_id)
            if table is None:
                raise KeyError(
                    f"unknown set id {set_id!r}; have {sorted(self._tables)}"
                )
            for record in table.query(contig, start, end, boundary):
                if emitted % page_size == 0:
                    self.counters.add_request()
                emitted += 1
                yield record
        if emitted == 0:
            self.counters.add_request()  # the empty page

    def search_variants(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = FILE_PAGE_SIZE,
    ) -> Iterator[Dict]:
        return self._search(
            request["variantSetIds"], request, boundary, page_size
        )

    def search_reads(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = FILE_PAGE_SIZE,
    ) -> Iterator[Dict]:
        return self._search(
            request["readGroupSetIds"], request, boundary, page_size
        )


class FileGenomicsSource(GenomicsSource):
    """Local files behind the :class:`GenomicsSource` seam.

    ``paths`` maps each file to a set id (``file_set_ids``); each file parses
    once, lazily, under a lock (per-shard worker threads all call
    :meth:`client` concurrently — without the lock each would re-parse every
    file) and the tables are shared by every client session.
    """

    def __init__(
        self,
        paths: Sequence[str],
        stream_chunk_bytes: Optional[int] = None,
        ingest_workers: Optional[int] = None,
    ):
        if not paths:
            raise ValueError("--source file needs --input-files")
        self.paths = list(paths)
        self.set_ids = file_set_ids(self.paths)
        self._by_id = dict(zip(self.set_ids, self.paths))
        self._tables: Dict[str, _FileTable] = {}
        self._packed: Dict[str, _PackedVcf] = {}
        self._streamed: Dict[str, _StreamedVcf] = {}
        #: ``None`` = auto (stream VCFs past ``STREAM_THRESHOLD_BYTES``),
        #: ``0`` = never stream, ``> 0`` = always stream with this chunk.
        self.stream_chunk_bytes = stream_chunk_bytes
        #: Chunk-parallel ingest threads (``--ingest-workers``): ``None`` =
        #: auto (:func:`default_ingest_workers`), ``0`` = the serial oracle
        #: path. Validated here so a bad value fails at construction, not
        #: from a worker thread mid-parse.
        self.ingest_workers = ingest_workers
        _resolve_ingest_workers(ingest_workers)
        #: Sets whose AUTO-selected streaming failed the coordinate-order
        #: probe and fell back to the in-memory path (with a warning).
        self._no_stream: set = set()
        # The leaf-ness below is machine-checked: `graftcheck lockgraph`
        # builds the static acquisition-order graph and fails CI if this
        # node ever grows an edge into a cycle, or is held across a device
        # sync / blocking queue op (check/lockgraph.py, GL001-GL004).
        # lock order: leaf lock guarding the parsed-view caches; held only
        # around dict get/insert (parses happen inside, but never take
        # another lock — the parse pool's workers are lock-free).
        self._lock = threading.Lock()

    def _table(self, set_id: str) -> _FileTable:
        with self._lock:
            table = self._tables.get(set_id)
            if table is None:
                if set_id not in self._by_id:
                    raise KeyError(
                        f"unknown set id {set_id!r}; inputs are {self.set_ids}"
                    )
                table = _FileTable(self._by_id[set_id], set_id)
                self._tables[set_id] = table
            return table

    def client(self) -> FileClient:
        # Materialize every table so client sessions share one parsed copy.
        for set_id in self.set_ids:
            self._table(set_id)
        return FileClient(self._tables)

    # -------------------------------------------------------- streaming mode

    def _is_vcf(self, set_id: str) -> bool:
        path = self._by_id.get(set_id, "")
        lowered = path[:-3] if path.endswith(".gz") else path
        return lowered.endswith(".vcf") and not os.path.isdir(path)

    def wants_streaming(self, set_id: str) -> bool:
        """Whether this set's packed ingest should stream (bounded memory)
        rather than load: explicit via ``stream_chunk_bytes`` (0 = never,
        > 0 = always), else automatic past ``STREAM_THRESHOLD_BYTES``.
        Only VCFs stream; other formats keep the in-memory tables. Sets
        whose auto-selected streaming already failed the sortedness probe
        report False (they fell back to the in-memory path)."""
        if not self._is_vcf(set_id):
            return False
        if self.stream_chunk_bytes is not None:
            return self.stream_chunk_bytes > 0
        if set_id in self._no_stream:
            return False
        path = self._by_id[set_id]
        try:
            size = os.path.getsize(path)
        except OSError:
            return False
        if path.endswith(".gz"):
            # The threshold is in DECOMPRESSED bytes; estimate from the
            # compressed size (exact sizing would require reading the file).
            size *= _GZ_RATIO_ESTIMATE
        return size > STREAM_THRESHOLD_BYTES

    def streamed(self, set_id: str) -> _StreamedVcf:
        """The streaming view of one VCF input (header parsed once; data
        never resident)."""
        with self._lock:
            view = self._streamed.get(set_id)
            if view is None:
                if set_id not in self._by_id:
                    raise KeyError(
                        f"unknown set id {set_id!r}; inputs are {self.set_ids}"
                    )
                view = _StreamedVcf(
                    self._by_id[set_id],
                    set_id,
                    chunk_bytes=self.stream_chunk_bytes or STREAM_CHUNK_BYTES,
                    ingest_workers=self.ingest_workers,
                )
                self._streamed[set_id] = view
            return view

    def _auto_stream_verified(self, set_id: str) -> bool:
        """The ADVICE.md sharp-edge fix: AUTO-selected streaming verifies
        coordinate-sortedness up front (a cached site-only pass — the same
        scan lazy contig discovery runs, O(chunk) memory, no genotype walk)
        instead of hard-erroring mid-ingest. An unsorted file warns and
        falls back to the in-memory path; EXPLICIT ``--stream-chunk-bytes N``
        skips the probe and keeps the hard error (the flag asserts the
        input is sorted; a silent O(file) fallback would betray exactly the
        memory bound the user demanded)."""
        if self.stream_chunk_bytes is not None:
            return True  # explicit: trusted, hard error downstream
        if set_id in self._no_stream:
            return False
        try:
            # Runs (and caches) the order-checked site scan; sorted files
            # reuse the result for contig discovery.
            self.streamed(set_id).contig_bounds()
        except UnsortedVcfError as e:
            warnings.warn(
                f"auto-selected streaming ingest found an unsorted VCF "
                f"({e}); falling back to the in-memory parse — peak host "
                "memory is O(file), not O(chunk). Sort the input to "
                "restore bounded-memory streaming, or pass "
                "--stream-chunk-bytes 0 to choose the in-memory path "
                "explicitly and skip this probe.",
                RuntimeWarning,
                stacklevel=3,
            )
            with self._lock:
                self._no_stream.add(set_id)
                self._streamed.pop(set_id, None)
            return False
        return True

    def _packed_blocks(
        self,
        view: "_PackedVcf",
        shard: Contig,
        block_size: int,
        min_allele_frequency: Optional[float],
        counters: Optional[StreamCounters] = None,
        shard_index: Optional[int] = None,
    ) -> Iterator[Dict]:
        """Dense blocks for ONE shard window from the in-memory packed
        view — the shared body of the packed fast path and the unsorted-VCF
        fallback (whose ``counters`` must match what the streaming pass
        would have recorded: pre-filter rows per shard, post-filter kept
        variants)."""
        positions, af, hv = view.window(shard)
        if counters is not None and shard_index is not None and len(positions):
            counters.add_shard_rows(shard_index, len(positions))
        if min_allele_frequency is not None:
            # The reference's rule (``VariantsPca.scala:136-148``): strictly
            # greater, first AF value, records without AF dropped (NaN here;
            # NaN > t is False, so absent/unparseable AF never passes).
            keep = af > min_allele_frequency
            positions, af, hv = positions[keep], af[keep], hv[keep]
        for off in range(0, len(positions), block_size):
            hv_block = hv[off : off + block_size]
            nonzero = hv_block.any(axis=1)
            if not nonzero.any():
                continue
            if counters is not None:
                counters.add_variants(int(nonzero.sum()))
            yield {
                "positions": positions[off : off + block_size][nonzero],
                "has_variation": hv_block[nonzero].astype(np.uint8),
                "af": af[off : off + block_size][nonzero],
            }

    def stream_genotype_blocks(
        self,
        variant_set_id: str,
        shards: Sequence[Contig],
        block_size: int = 1024,
        min_allele_frequency: Optional[float] = None,
        counters: Optional[StreamCounters] = None,
    ) -> Iterator[Dict]:
        """One bounded-memory pass serving EVERY shard window (file order;
        the Gramian sum commutes). See ``_StreamedVcf.stream_blocks``.

        When the set was auto-selected for streaming but fails the
        sortedness probe (:meth:`_auto_stream_verified`), the same block
        stream — identical dicts, identical counter accounting — is served
        from the in-memory packed view instead, so a caller that already
        chose the streaming path degrades without re-planning."""
        if self._auto_stream_verified(variant_set_id):
            yield from self.streamed(variant_set_id).stream_blocks(
                shards,
                block_size=block_size,
                min_allele_frequency=min_allele_frequency,
                counters=counters,
            )
            return
        view = self.packed(variant_set_id)
        for idx, shard in enumerate(shards):
            yield from self._packed_blocks(
                view,
                shard,
                block_size,
                min_allele_frequency,
                counters=counters,
                shard_index=idx,
            )

    # ------------------------------------------------------ packed fast path

    def packed(self, set_id: str) -> _PackedVcf:
        """The column-oriented packed view of one VCF input (native parser
        when available), parsed once under the same lock discipline as the
        wire tables."""
        with self._lock:
            view = self._packed.get(set_id)
            if view is None:
                if set_id not in self._by_id:
                    raise KeyError(
                        f"unknown set id {set_id!r}; inputs are {self.set_ids}"
                    )
                view = _PackedVcf(
                    self._by_id[set_id],
                    set_id,
                    ingest_workers=self.ingest_workers,
                )
                self._packed[set_id] = view
            return view

    def genotype_blocks(
        self,
        variant_set_id: str,
        contig: Contig,
        block_size: int = 1024,
        min_allele_frequency: Optional[float] = None,
    ) -> Iterator[Dict]:
        """Packed fast path: dense has-variation blocks for the Gramian —
        the same contract as the synthetic source's ``genotype_blocks``
        (AF-filtered, all-zero-variation rows dropped, the
        ``filter(_.size > 0)`` stage of ``VariantsPca.scala:206``).

        Streaming sets serve the window from a bounded-memory pass — one
        full decompress+parse pass of the file PER CALL, deliberately: the
        alternative (falling back to the in-memory view) would silently
        hold an O(file) parse of exactly the inputs streaming exists to
        bound. Multi-window callers on streaming sets must use
        :meth:`stream_genotype_blocks`, which serves every window in one
        pass (the driver does)."""
        if self.wants_streaming(variant_set_id) and self._auto_stream_verified(
            variant_set_id
        ):
            yield from self.stream_genotype_blocks(
                variant_set_id,
                [contig],
                block_size=block_size,
                min_allele_frequency=min_allele_frequency,
            )
            return
        yield from self._packed_blocks(
            self.packed(variant_set_id), contig, block_size,
            min_allele_frequency,
        )

    def page_requests(
        self, variant_set_id: str, contig: Contig, bases_per_partition: int
    ) -> int:
        """Wire-equivalent request accounting for a packed scan of
        ``contig``: one request per ``FILE_PAGE_SIZE`` records per shard, at
        least one per shard — exactly what ``FileClient.search_variants``
        counts, so I/O stats agree between the wire and packed paths."""
        view = self.packed(variant_set_id)
        total = 0
        for shard in contig.get_shards(bases_per_partition):
            rows = len(view.window(shard)[0])
            total += max(1, -(-rows // FILE_PAGE_SIZE))
        return total

    def search_callsets(self, variant_set_ids: Sequence[str]) -> List[Dict]:
        out: List[Dict] = []
        seen = set()
        for set_id in variant_set_ids:
            if set_id in seen:
                continue
            seen.add(set_id)
            if set_id not in self._tables and self._is_vcf(set_id):
                # VCF callsets come from the #CHROM header alone (identical
                # to the full parse's list) — a multi-GB VCF must not pay a
                # whole-file wire parse just to learn its cohort.
                out.extend(self.streamed(set_id).callsets)
                continue
            out.extend(self._table(set_id).callsets)
        return out

    def get_contigs(
        self,
        variant_set_id: str,
        sex_filter: SexChromosomeFilter = SexChromosomeFilter.INCLUDE_XY,
    ) -> List[Contig]:
        from spark_examples_tpu.utils.native import vcf_library

        path = self._by_id.get(variant_set_id)
        lowered = (
            path[:-3] if path and path.endswith(".gz") else (path or "")
        )
        if self.wants_streaming(variant_set_id) and self._auto_stream_verified(
            variant_set_id
        ):
            # Lazy discovery: a site-only streaming pass (CHROM/POS/REF —
            # no genotype walk) learns the bounds in O(chunk) memory; the
            # result matches the packed view's ``contig_bounds``. The probe
            # above already ran (and cached) this scan for auto mode;
            # explicit streaming pays it here, where UnsortedVcfError
            # remains the documented hard error.
            contigs = [
                Contig(name, 0, bound)
                for name, bound in sorted(
                    self.streamed(variant_set_id).contig_bounds().items()
                )
            ]
            return filter_sex_chromosomes(contigs, sex_filter)
        with self._lock:
            packed = self._packed.get(variant_set_id)
            have_table = variant_set_id in self._tables
        if (
            packed is None
            and not have_table
            and lowered.endswith(".vcf")
            and vcf_library() is not None
        ):
            # Neither view exists yet: the native packed parse is the cheap
            # way to learn the contig extents (a packed --all-references run
            # would otherwise pay the full per-record Python parse here).
            packed = self.packed(variant_set_id)
        if packed is not None:
            contigs = [
                Contig(name, 0, bound)
                for name, bound in sorted(packed.contig_bounds.items())
            ]
            return filter_sex_chromosomes(contigs, sex_filter)
        return filter_sex_chromosomes(
            self._table(variant_set_id).contigs(), sex_filter
        )


__all__ = [
    "FileGenomicsSource",
    "FileClient",
    "StreamCounters",
    "UnsortedVcfError",
    "af_float",
    "default_ingest_workers",
    "file_set_id",
    "file_set_ids",
]

"""THE windowed, contig-ordered stream abstraction — every byte of ingest
in this tree flows through here.

The reference delegated memory discipline to Spark's partition model (one
page per executor, ``rdd/VariantsRDD.scala:198-225``); this module is the
TPU-native replacement's equivalent, stated once and adopted by every
source (``sources/files.py``, ``sources/rest.py``, ``sources/synthetic.py``)
and every host-side consumer (``pipeline/``):

- **Bounded windows + partial-record carry** (:func:`iter_byte_windows`,
  :func:`iter_text_lines`): a (possibly gzipped) file is read in
  ``window_bytes`` pieces cut at line boundaries, the partial last line
  carried into the next window. Peak residency is one window plus the
  longest record — never the file, and for ``.gz`` inputs never the
  compressed copy beside more than one decompressed window (gzip's
  internal read buffer is O(KB); decompression happens window by window).
- **Sortedness probe** (:class:`SortednessProbe`): the single-pass
  contract — each contig's records contiguous and non-decreasing in
  position — checked as the stream advances, turning a silently-wrong
  one-pass consumer into a loud error naming the fix.
- **Budgeted accumulators** (:class:`ChunkedArrayBuilder`,
  :class:`SpooledRecordTable`): the ONLY blessed accumulation shapes.
  ``graftcheck hostmem`` forbids every raw ``append``/``extend`` of
  stream-tainted data (GH002) and — since the inventory hit zero — the
  ``hostmem(unbounded)`` escape hatch itself (GH006). What replaces them
  is charged in the closed-form bound (``parallel/mesh.py:
  host_peak_bytes``) and **capacity-enforced at runtime**: exceeding the
  declared bound raises :class:`StreamBudgetError` instead of growing,
  so the static proof is also a live invariant.
- **Streaming k-way merge-join** (:func:`merge_join`): multi-set cohorts
  join key-sorted record streams holding one key group at a time
  (≤ k × per-key duplicates), never a materialized per-set table.

``graftcheck``'s GC012 lint rule forbids raw file-handle iteration in
``sources/`` and ``pipeline/`` outside this module — there is exactly one
place that reads data files, and it is bounded by construction.
"""

from __future__ import annotations

import gzip
import heapq
import json
import os
import struct
import tempfile
from typing import (
    Any,
    BinaryIO,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
    TypeVar,
)

import numpy as np

from spark_examples_tpu.utils import faults

T = TypeVar("T")

#: Smallest honored window: guards zero/negative requests while letting
#: tests fuzz chunk boundaries with windows smaller than one line (the
#: carry handles lines longer than the window).
WINDOW_FLOOR_BYTES = 64

#: Default window for line-oriented readers that do not inherit a chunk
#: size from their caller (header scans, JSONL part files). Deliberately
#: small: decode + line split transiently hold ~3× the window, and the
#: streamed-ingest memory regression tests pin peak RSS to O(chunk).
DEFAULT_WINDOW_BYTES = 256 << 10

#: Floor on a well-formed wire data line: a minimal VCF data line is 8
#: single-character mandatory fields + 7 tabs + newline = 16 bytes; JSONL
#: and SAM minima are larger. ``decompressed_size / 16`` therefore bounds
#: the row count of ANY wire input — the closed-form row bound
#: ``conf_host_peak_bytes`` charges and the spooled tables enforce.
MIN_WIRE_LINE_BYTES = 16

#: Sound-by-contract cap on a gzip member's decompression ratio. Single
#: member archives < 4 GiB are sized EXACTLY from the ISIZE trailer (RFC
#: 1952); multi-member archives (bgzip) and ≥ 4 GiB streams fall back to
#: on-disk size × this ratio. Real VCF genotype matrices compress 10-30×;
#: DEFLATE's absolute maximum is ~1032×. 128 leaves a 4× margin over real
#: data while keeping the static bound finite — and the budgeted builders
#: enforce the same cap at runtime, so a pathological archive fails
#: loudly (:class:`StreamBudgetError`) instead of exceeding the proof.
GZ_DECOMPRESS_RATIO_BOUND = 128

#: Charged bytes per spooled-table index row: three int64 index columns
#: (start, offset, length — 24 B) plus build-time Python-int slack before
#: the arrays freeze. The records themselves live on disk.
SPOOL_INDEX_BYTES_PER_ROW = 128


class StreamBudgetError(RuntimeError):
    """A budgeted accumulator was asked to exceed its declared capacity —
    the runtime face of the ``graftcheck hostmem`` closed-form bound. The
    input violated a contract the bound was derived from (e.g. a gzip
    archive past :data:`GZ_DECOMPRESS_RATIO_BOUND`); the fix is the
    input, never a bigger silent allocation."""


class UnsortedStreamError(ValueError):
    """A single-pass consumer met records out of contig-contiguous,
    position-sorted order (see :class:`SortednessProbe`)."""


# --------------------------------------------------------------- file facts


def open_binary(path: str) -> BinaryIO:
    """The one opener: transparent gzip, binary mode. Every data-file
    handle in ``sources/``/``pipeline/`` originates here (GC012)."""
    if path.endswith(".gz"):
        return gzip.open(path, "rb")  # type: ignore[return-value]
    return open(path, "rb")


def decompressed_size_bound(path: str) -> int:
    """Finite upper bound on ``path``'s decompressed byte size, from
    on-disk metadata alone (no data pass).

    Plain files: exact (``st_size``). ``.gz``: the RFC 1952 ISIZE trailer
    — exact for the standard single-member archive under 4 GiB — taken
    together with on-disk size × :data:`GZ_DECOMPRESS_RATIO_BOUND` so
    multi-member (bgzip) and ≥ 4 GiB streams stay soundly bounded. An
    unreadable path bounds at 0 (the caller layers its contract-level
    fallback; see ``check/hostmem.py:conf_host_peak_bytes``)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if not path.endswith(".gz"):
        return int(size)
    isize = 0
    if size >= 18:  # minimal gzip member: 10B header + 8B trailer
        try:
            with open(path, "rb") as f:
                f.seek(-4, os.SEEK_END)
                (isize,) = struct.unpack("<I", f.read(4))
        except OSError:
            isize = 0
    return max(int(isize), int(size) * GZ_DECOMPRESS_RATIO_BOUND)


def wire_rows_bound(path: str) -> int:
    """Closed-form bound on the wire-record count of one input file:
    ``decompressed_size_bound / MIN_WIRE_LINE_BYTES``, plus one for a
    final unterminated line. 0-byte (or unreadable) paths bound at 1."""
    return decompressed_size_bound(path) // MIN_WIRE_LINE_BYTES + 1


# ---------------------------------------------------------------- windowing


def iter_byte_windows(
    path: str,
    window_bytes: int,
    *,
    fault_label: str = "files.read",
) -> Iterator[bytes]:
    """Stream a (possibly gzipped) text file in ~``window_bytes`` pieces
    that end at line boundaries — the partial last line carries into the
    next window, so concatenating the windows reproduces the decompressed
    bytes exactly and no record is ever split.

    Peak residency: one window + one carry (≤ the longest line). For
    ``.gz`` inputs decompression happens through gzip's windowed read —
    the compressed buffer held at any instant is gzip's internal O(KB)
    read-ahead, never the whole file, and never beside more than one
    decompressed window (the co-residency contract, regression-tested).
    """
    window_bytes = max(WINDOW_FLOOR_BYTES, int(window_bytes))
    carry = b""
    with open_binary(path) as f:
        while True:
            # Registered IO fault boundary (utils/faults.py): a plan entry
            # can fail, truncate, or delay exactly one windowed read here —
            # the reproducible stand-in for a failing disk.
            data = faults.io_point(fault_label, f.read(window_bytes))
            if not data:
                break
            if carry:
                data = carry + data
            cut = data.rfind(b"\n")
            if cut < 0:
                carry = data
                continue
            carry = data[cut + 1 :]
            yield data[: cut + 1]
    if carry:
        yield carry


def iter_text_lines(
    path: str,
    window_bytes: int = DEFAULT_WINDOW_BYTES,
    *,
    fault_label: str = "files.read",
) -> Iterator[str]:
    """Decoded lines of a (possibly gzipped) text file, without their
    terminators, in O(window) memory — the streaming replacement for
    ``for line in open(path)``. Newline handling matches text-mode
    universal newlines (``\\r\\n`` and lone ``\\r`` break lines), so a
    consumer migrated from a raw text handle sees identical lines."""
    for window in iter_byte_windows(
        path, window_bytes, fault_label=fault_label
    ):
        text = window.decode("utf-8")
        if "\r" in text:
            text = text.replace("\r\n", "\n").replace("\r", "\n")
        lines = text.split("\n")
        # A line-aligned window ends with '\n' (final empty piece); the
        # last carry may not — its final piece is a real unterminated line.
        tail = lines.pop()
        for line in lines:
            yield line
        if tail:
            yield tail


def windowed(items: Iterable[T], size: int) -> Iterator[List[T]]:
    """Generic bounded windowing of an object stream: lists of at most
    ``size`` items, in order — the page/window shape shared by the REST
    paginator and the synthetic generator so every source speaks the same
    bounded contract."""
    if size <= 0:
        raise ValueError(f"window size must be >= 1, got {size}")
    window: List[T] = []
    for item in items:
        window.append(item)
        if len(window) >= size:
            yield window
            window = []
    if window:
        yield window


# --------------------------------------------------------- sortedness probe


class SortednessProbe:
    """Single-pass ordering contract for one stream: each contig's records
    must be contiguous and non-decreasing in position (the standard
    coordinate-sorted layout). ``check`` takes one same-contig run at a
    time; violations raise ``error_cls`` with a message naming the fix.

    This is the generalization of the VCF streaming path's run-order
    guard — ``hint`` carries the source-specific remedy (e.g. "sort the
    input or disable streaming")."""

    def __init__(
        self,
        label: str,
        *,
        error_cls: Callable[[str], Exception] = UnsortedStreamError,
        hint: str = "",
    ):
        self.label = label
        self.error_cls = error_cls
        self.hint = f"; {hint}" if hint else ""
        self.current: Optional[str] = None
        self.last_pos = -1
        self.finished: set = set()

    def check(self, name: str, positions: "np.ndarray") -> None:
        if name != self.current:
            if self.current is not None:
                self.finished.add(self.current)
            if name in self.finished:
                raise self.error_cls(
                    f"{self.label}: records for contig {name!r} are not "
                    "contiguous — a single streaming pass needs "
                    f"contig-contiguous input{self.hint}"
                )
            self.current = name
            self.last_pos = -1
        if len(positions) == 0:
            return
        if int(positions[0]) < self.last_pos or (
            len(positions) > 1 and bool(np.any(np.diff(positions) < 0))
        ):
            raise self.error_cls(
                f"{self.label}: contig {name!r} positions are not sorted — "
                f"a single streaming pass needs sorted positions{self.hint}"
            )
        self.last_pos = int(positions[-1])


# ----------------------------------------------------- budgeted accumulators


class ChunkedArrayBuilder:
    """Bounded-growth array accumulator: parts append into a preallocated
    buffer grown by doubling through slice assignment — the audited
    bounded-staging idiom — with an optional hard row capacity enforced at
    runtime (:class:`StreamBudgetError`). The one blessed way to assemble
    a column from a windowed stream; its residency (≤ 2× final size
    during a growth step) is charged by the packed-table term of
    ``parallel/mesh.py:host_peak_bytes``."""

    def __init__(
        self,
        dtype: Any,
        row_shape: Tuple[int, ...] = (),
        capacity_rows: Optional[int] = None,
        label: str = "stream",
    ):
        self.dtype = np.dtype(dtype)
        self.row_shape = tuple(int(d) for d in row_shape)
        self.capacity_rows = (
            None if capacity_rows is None else int(capacity_rows)
        )
        self.label = label
        self.rows = 0
        self._buf = np.empty((0,) + self.row_shape, dtype=self.dtype)

    def add(self, part: "np.ndarray") -> None:
        part = np.asarray(part, dtype=self.dtype)
        n = part.shape[0]
        if n == 0:
            return
        new_rows = self.rows + n
        if self.capacity_rows is not None and new_rows > self.capacity_rows:
            raise StreamBudgetError(
                f"{self.label}: {new_rows} rows exceed the declared "
                f"capacity of {self.capacity_rows} — the input violates "
                "the bound this run was admitted under"
            )
        if new_rows > self._buf.shape[0]:
            grown = np.empty(
                (max(new_rows, 2 * self._buf.shape[0]),) + self.row_shape,
                dtype=self.dtype,
            )
            grown[: self.rows] = self._buf[: self.rows]
            self._buf = grown
        self._buf[self.rows : new_rows] = part
        self.rows = new_rows

    def finish(self) -> "np.ndarray":
        """The accumulated rows (a view; no copy)."""
        return self._buf[: self.rows]


class SpooledRecordTable:
    """Per-contig start-sorted wire-record table whose RECORDS live in an
    unlinked disk spool (JSON lines) — resident memory is the integer
    index (:data:`SPOOL_INDEX_BYTES_PER_ROW` per row) plus one decode
    window, never O(file). This is how the wire-oracle VCF/JSONL/SAM
    tables stream: random-access bisect queries read records back lazily
    via ``os.pread`` (thread-safe, no shared seek state), byte-identical
    to the retired in-memory tables (JSON round-trips every wire dict).

    Rows are capacity-enforced against the closed-form bound
    (``wire_rows_bound``); ``finish`` freezes the index with a per-contig
    stable sort by start — identical ordering to the retired
    ``_finish_tables`` (equal starts keep insertion order)."""

    def __init__(self, label: str, capacity_rows: Optional[int] = None):
        self.label = label
        self.capacity_rows = (
            None if capacity_rows is None else int(capacity_rows)
        )
        self.rows_total = 0
        self._finished = False
        self._spool = tempfile.TemporaryFile(prefix="graft-spool-")
        self._offset = 0
        self._starts: Dict[str, List[int]] = {}
        self._offsets: Dict[str, List[int]] = {}
        self._lengths: Dict[str, List[int]] = {}
        self._index: Dict[
            str, Tuple["np.ndarray", "np.ndarray", "np.ndarray"]
        ] = {}

    def add(self, contig: str, start: int, record: Dict[str, Any]) -> None:
        if self._finished:
            raise ValueError(f"{self.label}: table already finished")
        if (
            self.capacity_rows is not None
            and self.rows_total >= self.capacity_rows
        ):
            raise StreamBudgetError(
                f"{self.label}: row {self.rows_total + 1} exceeds the "
                f"declared capacity of {self.capacity_rows} — the input "
                "violates the bound this run was admitted under"
            )
        data = json.dumps(record, separators=(",", ":")).encode("utf-8")
        self._spool.write(data)
        self._spool.write(b"\n")
        self._starts.setdefault(contig, []).append(int(start))
        self._offsets.setdefault(contig, []).append(self._offset)
        self._lengths.setdefault(contig, []).append(len(data))
        self._offset += len(data) + 1
        self.rows_total += 1

    def finish(self) -> "SpooledRecordTable":
        """Flush the spool and freeze the index, start-sorted per contig
        (stable — duplicate starts keep insertion order)."""
        if self._finished:
            return self
        self._finished = True
        self._spool.flush()
        for contig, starts in self._starts.items():
            arr = np.asarray(starts, dtype=np.int64)
            order = np.argsort(arr, kind="stable")
            self._index[contig] = (
                arr[order],
                np.asarray(self._offsets[contig], dtype=np.int64)[order],
                np.asarray(self._lengths[contig], dtype=np.int64)[order],
            )
        self._starts.clear()
        self._offsets.clear()
        self._lengths.clear()
        return self

    # ----------------------------------------------------------- queries

    def contig_names(self) -> List[str]:
        self._need_finished()
        return list(self._index)

    def rows(self, contig: str) -> int:
        self._need_finished()
        idx = self._index.get(contig)
        return 0 if idx is None else int(idx[0].shape[0])

    def starts(self, contig: str) -> "np.ndarray":
        """Start-sorted positions of one contig (int64; empty if absent)."""
        self._need_finished()
        idx = self._index.get(contig)
        return np.empty(0, np.int64) if idx is None else idx[0]

    def record(self, contig: str, i: int) -> Dict[str, Any]:
        """One record, decoded from the spool (``os.pread`` — safe from
        concurrent per-shard workers; no seek state)."""
        self._need_finished()
        _, offsets, lengths = self._index[contig]
        data = os.pread(
            self._spool.fileno(), int(lengths[i]), int(offsets[i])
        )
        decoded: Dict[str, Any] = json.loads(data)
        return decoded

    def iter_records(
        self, contig: str, lo: int = 0, hi: Optional[int] = None
    ) -> Iterator[Dict[str, Any]]:
        """Records ``[lo, hi)`` of one contig in start order, decoded one
        at a time — the O(window) query surface bisect consumers stream
        from."""
        self._need_finished()
        if contig not in self._index:
            return
        n = self.rows(contig)
        hi = n if hi is None else min(int(hi), n)
        for i in range(max(0, int(lo)), hi):
            yield self.record(contig, i)

    def tail_records(self, contig: str, n: int) -> List[Dict[str, Any]]:
        """The last ``n`` records of one contig (bounded helper for span
        estimation)."""
        total = self.rows(contig)
        return [
            self.record(contig, i) for i in range(max(0, total - n), total)
        ]

    def close(self) -> None:
        try:
            self._spool.close()
        except OSError:
            pass

    def _need_finished(self) -> None:
        if not self._finished:
            raise ValueError(f"{self.label}: finish() the table first")


# --------------------------------------------------------------- merge-join


class MergeJoinStats:
    """Observability for :func:`merge_join`'s bounded-window claim: the
    peak number of records tracked at once (one key group: ≤ k × per-key
    duplicates) and the group count — what the property test asserts
    against ``k × window``."""

    def __init__(self) -> None:
        self.peak_tracked = 0
        self.groups = 0

    def add_group(self, tracked: int) -> None:
        """Account one emitted key group holding ``tracked`` records."""
        self.groups += 1
        if tracked > self.peak_tracked:
            self.peak_tracked = tracked


def merge_join(
    streams: Sequence[Iterator[Tuple[Any, Any]]],
    stats: Optional[MergeJoinStats] = None,
) -> Iterator[Tuple[Any, List[List[Any]]]]:
    """Streaming k-way merge-join over key-sorted ``(key, record)``
    streams: yields ``(key, per_stream_records)`` for every key present
    in ANY stream, keys ascending, holding exactly one key group in
    memory (≤ k × that key's duplicate count) — the bounded replacement
    for materializing per-set tables before a multi-set join.

    Each stream must be non-decreasing in key (checked;
    :class:`UnsortedStreamError` on regression). Join policy — inner,
    intersection, count thresholds — is the caller's: every per-stream
    list is present (possibly empty), so any policy is a filter over the
    yielded groups."""
    k = len(streams)
    iters = [iter(s) for s in streams]
    heap: List[Tuple[Any, int, Any]] = []
    last_key: List[Optional[Any]] = [None] * k
    for i, it in enumerate(iters):
        for key, record in it:
            heap.append((key, i, record))
            last_key[i] = key
            break
    heapq.heapify(heap)

    def _pull(i: int) -> None:
        for key, record in iters[i]:
            prev = last_key[i]
            if prev is not None and key < prev:
                raise UnsortedStreamError(
                    f"merge_join: stream {i} key {key!r} regressed below "
                    f"{prev!r} — merge-join needs key-sorted streams"
                )
            last_key[i] = key
            heapq.heappush(heap, (key, i, record))
            break

    while heap:
        group_key = heap[0][0]
        group: List[List[Any]] = [[] for _ in range(k)]
        tracked = 0
        while heap and heap[0][0] == group_key:
            _, i, record = heapq.heappop(heap)
            group[i].append(record)
            tracked += 1
            _pull(i)
        if stats is not None:
            stats.add_group(tracked)
        yield group_key, group


__all__ = [
    "ChunkedArrayBuilder",
    "DEFAULT_WINDOW_BYTES",
    "GZ_DECOMPRESS_RATIO_BOUND",
    "MIN_WIRE_LINE_BYTES",
    "MergeJoinStats",
    "SPOOL_INDEX_BYTES_PER_ROW",
    "SortednessProbe",
    "SpooledRecordTable",
    "StreamBudgetError",
    "UnsortedStreamError",
    "WINDOW_FLOOR_BYTES",
    "decompressed_size_bound",
    "iter_byte_windows",
    "iter_text_lines",
    "merge_join",
    "open_binary",
    "windowed",
    "wire_rows_bound",
]

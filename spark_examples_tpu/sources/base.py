"""Genomics source abstraction: the seam the reference never had.

The reference streams variants/reads from the live Google Genomics REST API
through ``Client`` + ``Paginator`` (``Client.scala:42-54``,
``rdd/VariantsRDD.scala:200-207``) and its authors noted the missing test seam
in-code (``SearchVariantsExample.scala:74-76``). Here the seam is first-class:

- :class:`GenomicsSource` — a backend (synthetic, REST, file) that can open
  per-partition :class:`GenomicsClient` sessions and answer driver-side
  metadata queries (callsets, contigs).
- :class:`GenomicsClient` — a per-partition session with the reference's I/O
  health counters (``initializedRequestsCount`` etc., ``Client.scala:50-54``),
  flushed into dataset stats when a shard's iterator is exhausted
  (``rdd/VariantsRDD.scala:192-196,214-224``).
- :class:`ShardBoundary` — ``Paginator.ShardBoundary`` semantics
  (``rdd/VariantsRDD.scala:201``): ``STRICT`` counts a record in exactly one
  shard (the one containing its start); ``OVERLAPS`` returns every record
  overlapping the range.
"""

from __future__ import annotations

import enum
import json
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Dict, Iterator, List, Mapping, Optional, Sequence

from spark_examples_tpu.sharding.contig import Contig, SexChromosomeFilter


class ShardBoundary(enum.Enum):
    """``Paginator.ShardBoundary`` (used at ``rdd/VariantsRDD.scala:201``)."""

    STRICT = "strict"
    OVERLAPS = "overlaps"


@dataclass
class ClientCounters:
    """I/O health counters (``Client.scala:50-54``).

    Mutate through the ``add_*`` methods — the one place the counting
    semantics live (and the seam the graftcheck GC009 rule points ad-hoc
    ``counters.x += n`` sites at). Each client session is single-threaded
    (one per partition worker), so plain ints suffice; the aggregation
    into the registry-backed run stats happens at flush time
    (``pipeline/stats.py:add_client``).
    """

    initialized_requests: int = 0
    unsuccessful_responses: int = 0
    io_exceptions: int = 0
    retries: int = 0

    def add_request(self, n: int = 1) -> None:
        self.initialized_requests += n

    def add_unsuccessful_response(self, n: int = 1) -> None:
        self.unsuccessful_responses += n

    def add_io_exception(self, n: int = 1) -> None:
        self.io_exceptions += n

    def add_retry(self, n: int = 1) -> None:
        """One transient failure the client will retry after backoff —
        the manifest's transient-pressure signal (``io_retries_total``)."""
        self.retries += n


@dataclass(frozen=True)
class OfflineAuth:
    """A serializable auth token usable on workers (``Client.scala:32-40``)."""

    client_secrets_file: str
    access_token: Optional[str] = None


def get_access_token(
    client_secrets_file: str, application_name: str = "spark-examples-tpu"
) -> OfflineAuth:
    """``Authentication.getAccessToken`` (``Client.scala:33-39``).

    Reads the client-secrets file if present; the interactive OAuth prompt
    flow of the reference is not reproducible offline, so the token is
    whatever the secrets file carries (or None for the synthetic source,
    which needs no auth).
    """
    token = None
    try:
        with open(client_secrets_file) as f:
            secrets = json.load(f)
        token = secrets.get("access_token")
    except (OSError, ValueError):
        pass
    return OfflineAuth(client_secrets_file=client_secrets_file, access_token=token)


class GenomicsClient(ABC):
    """A per-partition session with request/failure counters."""

    def __init__(self) -> None:
        self.counters = ClientCounters()

    @abstractmethod
    def search_variants(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = 1024,
    ) -> Iterator[Dict]:
        """Yield variant wire-format dicts for a SearchVariants request
        (``rdd/VariantsRDD.scala:201-207``), counting one initialized request
        per page."""

    @abstractmethod
    def search_reads(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = 1024,
    ) -> Iterator[Dict]:
        """Yield read wire-format dicts for a SearchReads request
        (``rdd/ReadsRDD.scala:108-116``)."""


class GenomicsSource(ABC):
    """A genomics backend."""

    @abstractmethod
    def client(self) -> GenomicsClient:
        """Open a fresh session (one per partition, as in
        ``rdd/VariantsRDD.scala:200``)."""

    @abstractmethod
    def search_callsets(self, variant_set_ids: Sequence[str]) -> List[Dict]:
        """All callsets of the given variant sets, as ``{"id", "name"}`` dicts
        (``VariantsPca.scala:97-109``)."""

    @abstractmethod
    def get_contigs(
        self,
        variant_set_id: str,
        sex_filter: SexChromosomeFilter = SexChromosomeFilter.INCLUDE_XY,
    ) -> List[Contig]:
        """Contig bounds of a variant set
        (``Contig.getContigsInVariantSet``, used at ``GenomicsConf.scala:88``)."""

    def declared_sites(self, contig: Contig) -> int:
        """The contig's declared candidate-site weight — the balance input
        of the host → contig-partition split
        (``sharding/contig.py:partition_contigs_by_host``). Base sources
        declare the base range (sites ∝ bases is the honest prior for
        real data); the synthetic source overrides with its exact
        site-grid span."""
        return max(0, contig.range)


__all__ = [
    "ShardBoundary",
    "ClientCounters",
    "OfflineAuth",
    "get_access_token",
    "GenomicsClient",
    "GenomicsSource",
]

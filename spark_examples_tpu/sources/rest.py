"""Google Genomics v1beta2 REST backend.

The real-API counterpart of the reference's ``Client`` + ``Paginator``
(``Client.scala:42-54``; paging loop behavior of
``Paginator.Variants.create(...).search(req)`` at
``rdd/VariantsRDD.scala:201-207``): POST search requests, follow
``nextPageToken`` until exhausted, apply the shard-boundary filter
client-side, and count requests / unsuccessful responses / IO exceptions.

This environment has no network egress and the v1beta2 API itself has been
sunset, so this backend exists for API-shape parity and for deployments that
point ``base_url`` at a live, compatible endpoint (e.g. a GA4GH-style
server). All logic except the actual socket I/O is exercised by unit tests
via an injectable ``transport`` callable.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Iterator, List, Mapping, Optional, Sequence

from spark_examples_tpu.sharding.contig import Contig, SexChromosomeFilter, filter_sex_chromosomes
from spark_examples_tpu.sources.base import (
    GenomicsClient,
    GenomicsSource,
    OfflineAuth,
    ShardBoundary,
)
from spark_examples_tpu.utils import faults
from spark_examples_tpu.utils.retry import (
    full_jitter_delay,
    retry_after_seconds,
)

DEFAULT_BASE_URL = "https://www.googleapis.com/genomics/v1beta2"

#: transport(url, payload_dict, headers) -> response_dict
Transport = Callable[[str, Mapping, Mapping], Dict]


def _urllib_transport(url: str, payload: Mapping, headers: Mapping) -> Dict:
    data = json.dumps(payload).encode("utf-8")
    request = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json", **headers}
    )
    with urllib.request.urlopen(request, timeout=60) as response:
        return json.loads(response.read().decode("utf-8"))


def _retryable_http(code: int) -> bool:
    """5xx and 429 (rate-limit) are transient; other 4xx are caller errors
    that no retry can fix (a bad variant-set id stays bad)."""
    return code >= 500 or code == 429


class RestClient(GenomicsClient):
    def __init__(
        self,
        auth: Optional[OfflineAuth],
        base_url: str = DEFAULT_BASE_URL,
        transport: Transport = _urllib_transport,
        max_retries: int = 3,
        backoff_base: float = 0.5,
        backoff_cap: float = 8.0,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ):
        super().__init__()
        self.auth = auth
        self.base_url = base_url.rstrip("/")
        self.transport = transport
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()

    def _headers(self) -> Dict[str, str]:
        if self.auth and self.auth.access_token:
            return {"Authorization": f"Bearer {self.auth.access_token}"}
        return {}

    def _post(self, path: str, payload: Mapping) -> Dict:
        """POST with retries for transient failures only: exponential backoff
        with full jitter (the shared ``utils/retry.py`` arithmetic — delay
        uniform in ``[0, min(cap, base·2^attempt)]``) for 5xx/429/IO errors;
        a server-sent ``Retry-After`` on 429/503 is honored instead, capped
        by ``backoff_cap`` so a hostile or broken header can never park the
        pipeline. Non-retryable 4xx raises immediately. Every attempt and
        failure feeds the reference's accounting counters
        (``Client.scala:42-54``; report format ``pipeline/stats.py``), and
        every backoff counts into ``retries`` → the manifest's
        ``io_retries`` transient-pressure field."""
        url = f"{self.base_url}/{path}"
        last_error: Optional[Exception] = None
        for attempt in range(self.max_retries):
            self.counters.add_request()
            delay: Optional[float] = None
            try:
                # Registered IO fault boundary: one transport attempt
                # (ioerror here exercises this very retry loop).
                faults.io_point("rest.post")
                return self.transport(url, payload, self._headers())
            except urllib.error.HTTPError as e:
                self.counters.add_unsuccessful_response()
                if not _retryable_http(e.code):
                    raise RuntimeError(
                        f"request to {url} failed with HTTP {e.code} "
                        "(not retryable)"
                    ) from e
                last_error = e
                if e.code in (429, 503):
                    delay = retry_after_seconds(e.headers, self.backoff_cap)
            except (urllib.error.URLError, OSError) as e:
                self.counters.add_io_exception()
                last_error = e
            if attempt + 1 < self.max_retries:
                self.counters.add_retry()
                if delay is None:
                    delay = full_jitter_delay(
                        attempt, self.backoff_base, self.backoff_cap, self._rng
                    )
                self._sleep(delay)
        raise RuntimeError(f"request to {url} failed after retries") from last_error

    def _paginate(
        self, path: str, request: Mapping, items_field: str, page_size: int
    ) -> Iterator[Dict]:
        """One page resident at a time — the REST arm of the windowed
        stream discipline (``sources/stream.py``): each decoded page is
        re-yielded through :func:`windowed` (window = the requested page
        size), and a server page more than 4x the requested size raises
        :class:`StreamBudgetError` — a misbehaving server must fail
        loudly, not silently inflate host residency past the bound the
        prover charged for this source."""
        from spark_examples_tpu.sources.stream import (
            StreamBudgetError,
            windowed,
        )

        payload = dict(request)
        payload["pageSize"] = page_size
        token: Optional[str] = None
        while True:
            if token is not None:
                payload["pageToken"] = token
            response = self._post(path, payload)
            items = response.get(items_field, [])
            if len(items) > 4 * page_size:
                raise StreamBudgetError(
                    f"{path}: server returned {len(items)} items against "
                    f"pageSize {page_size} (>4x) — refusing to stage an "
                    "unbounded page on host"
                )
            for window in windowed(items, page_size):
                for item in window:
                    yield item
            token = response.get("nextPageToken")
            if not token:
                return

    def search_variants(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = 1024,
    ) -> Iterator[Dict]:
        start = int(request.get("start", 0))
        end = int(request.get("end", 1 << 62))
        for variant in self._paginate("variants/search", request, "variants", page_size):
            if boundary is ShardBoundary.STRICT:
                if not (start <= int(variant["start"]) < end):
                    continue
            yield variant

    def search_reads(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = 256,
    ) -> Iterator[Dict]:
        start = int(request.get("start", 0))
        end = int(request.get("end", 1 << 62))
        for read in self._paginate("reads/search", request, "alignments", page_size):
            position = int(read["alignment"]["position"]["position"])
            if boundary is ShardBoundary.STRICT and not (start <= position < end):
                continue
            yield read


class RestGenomicsSource(GenomicsSource):
    def __init__(
        self,
        auth: Optional[OfflineAuth] = None,
        base_url: str = DEFAULT_BASE_URL,
        transport: Transport = _urllib_transport,
    ):
        self.auth = auth
        self.base_url = base_url
        self.transport = transport

    def client(self) -> RestClient:
        return RestClient(self.auth, self.base_url, self.transport)

    def search_callsets(self, variant_set_ids: Sequence[str]) -> List[Dict]:
        """Driver-side callset fetch (``VariantsPca.scala:97-109``)."""
        client = self.client()
        return [
            {"id": cs["id"], "name": cs.get("name")}
            for cs in client._paginate(
                "callsets/search",
                {"variantSetIds": list(variant_set_ids)},
                "callSets",
                1024,
            )
        ]

    def get_contigs(
        self,
        variant_set_id: str,
        sex_filter: SexChromosomeFilter = SexChromosomeFilter.INCLUDE_XY,
    ) -> List[Contig]:
        """``Contig.getContigsInVariantSet`` over the variant-set metadata's
        ``referenceBounds`` (used at ``GenomicsConf.scala:88``)."""
        client = self.client()
        response = client._post(f"variantsets/{variant_set_id}", {})
        contigs = [
            Contig(b["referenceName"], 0, int(b["upperBound"]))
            for b in response.get("referenceBounds", [])
        ]
        return filter_sex_chromosomes(contigs, sex_filter)


__all__ = ["RestClient", "RestGenomicsSource", "DEFAULT_BASE_URL"]

from spark_examples_tpu.sources.base import (
    ClientCounters,
    GenomicsClient,
    GenomicsSource,
    OfflineAuth,
    ShardBoundary,
    get_access_token,
)
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

__all__ = [
    "ClientCounters",
    "GenomicsClient",
    "GenomicsSource",
    "OfflineAuth",
    "ShardBoundary",
    "get_access_token",
    "SyntheticGenomicsSource",
]

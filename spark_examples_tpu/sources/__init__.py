from spark_examples_tpu.sources.base import (
    ClientCounters,
    GenomicsClient,
    GenomicsSource,
    OfflineAuth,
    ShardBoundary,
    get_access_token,
)
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource


def partition_page_requests(
    source, variant_set_id, contig, bases_per_partition: int
) -> int:
    """Wire-equivalent page-request count for ONE shard of one variant
    set. The synthetic source's ``page_requests`` takes no set id (one
    synthetic wire serves every set); file/REST sources take it — this is
    the ONE home of that branch, shared by the PCA driver's and the
    analyses' ingest accounting so the two can never drift."""
    if isinstance(source, SyntheticGenomicsSource):
        return source.page_requests(contig, bases_per_partition)
    return source.page_requests(variant_set_id, contig, bases_per_partition)


__all__ = [
    "ClientCounters",
    "GenomicsClient",
    "GenomicsSource",
    "OfflineAuth",
    "ShardBoundary",
    "get_access_token",
    "partition_page_requests",
    "SyntheticGenomicsSource",
]

"""Deterministic synthetic genomics backend.

This is the fake-backend test seam the reference authors wished for
(``SearchVariantsExample.scala:74-76``) promoted to a first-class component,
and it doubles as the benchmark data plane.

Design rules:

- **Partition invariance.** Every random draw is counter-based hashing
  (splitmix64 finalizer) keyed by ``(seed, variant_set_id, contig, absolute
  position, stream, sample, allele)``. Any shard of any window therefore
  generates byte-identical records — the synthetic analog of
  ``ShardBoundary.STRICT`` exactness, and the property that makes
  determinism tests across device counts meaningful.
- **Population structure.** Samples are assigned to ``n_pops`` blocks with
  per-population allele-frequency shifts, so the flagship PCoA pipeline
  produces separable clusters (a meaningful end-to-end signal, not noise).
- **Two paths, one implementation.** The wire path yields the same JSON
  record shapes the reference's Java client deserializes; the packed path
  (:meth:`SyntheticGenomicsSource.genotype_blocks`) yields dense
  ``{0,1}`` has-variation blocks ready for the MXU Gramian. Both call the
  same ``_u01`` hash streams, and a test asserts they agree.
"""

from __future__ import annotations

import math
from typing import Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from spark_examples_tpu.constants import Examples
from spark_examples_tpu.sharding.contig import Contig, SexChromosomeFilter, filter_sex_chromosomes
from spark_examples_tpu.sources.base import (
    GenomicsClient,
    GenomicsSource,
    ShardBoundary,
)
from spark_examples_tpu.utils.murmur3 import murmur3_x64_128

_U64 = np.uint64
_P1 = _U64(0x9E3779B97F4A7C15)
_P2 = _U64(0xC2B2AE3D27D4EB4F)
_P3 = _U64(0x165667B19E3779F9)
_P4 = _U64(0xD6E8FEB86659FD93)

# Draw-stream tags.
_S_REF_BLOCK = 1
_S_AF = 2
_S_POP_BASE = 3  # stream 3+p for population p
_S_REF_BASE = 20
_S_ALT_BASE = 21
_S_GENOTYPE = 100
_S_READ_MAPQ = 200
_S_READ_BASEQ = 201
_S_READ_ALLELE = 202
_S_SOMATIC = 203
_S_GERMLINE_BASE = 204

_BASES = "ACGT"

#: SearchVariants page size of the synthetic wire path — request accounting
#: in the packed/device ingest paths mirrors it (one request per page per
#: shard, at least one per shard).
VARIANTS_PAGE_SIZE = 1024


def _af6(af: np.ndarray) -> np.ndarray:
    """Canonical 6-decimal AF, shared by every path.

    The wire format serializes AF as ``f"{af6:.6f}"`` and the reference's
    filter parses it back (``VariantsPca.scala:136-148``); rounding BEFORE
    serializing makes ``float(f"{_af6(af):.6f}") == _af6(af)`` an exact
    round-trip, so the packed/device paths (which compare ``_af6(af)``
    directly) and the wire path (which compares the parsed string) apply
    ``--min-allele-frequency`` identically on threshold-adjacent sites.
    (For Q32 allele frequencies ``k·2⁻³²``, ``af·1e6 = k·1e6·2⁻³² < 2⁵²`` is
    exact in float64, so NumPy's round-half-even here equals the integer
    rounding the device kernel uses.)
    """
    return np.round(np.asarray(af) * 1e6) / 1e6


# Fixed-point site-field constants (Q16/Q32). All site metadata is
# derived with u64-only arithmetic so the device ingest kernel
# (``ops/devicegen.py``) can recompute it bit-identically from positions
# alone — no per-site host→device traffic. The float forms used by the wire
# path are exact dyadic rationals (k·2⁻³²); the genotype draws compare
# against the Q32 integers directly (``_genotype_draw_pair``), identically
# on host and device.
_AF_BASE_Q32 = round(0.01 * 2**32)  # af = 0.01 + u²·0.49
_AF_SPAN_Q16 = round(0.49 * 2**16)
_POP_BASE_Q16 = round(0.25 * 2**16)  # af_pop = af·(0.25 + 1.5·u_p), clipped
_POP_SPAN_Q17 = round(1.5 * 2**16)
_POP_LO_Q32 = round(0.002 * 2**32)
_POP_HI_Q32 = round(0.95 * 2**32)


# Canonical AF-filter rule shared with the driver and device kernel.
from spark_examples_tpu.utils.af import af_filter_micro, af_passes  # noqa: E402


def _site_fields_q(site_key: np.uint64, positions: np.ndarray, ref_block_fraction: float, n_pops: int):
    """Integer site metadata: (is_ref_block, af_q32 (B,), af_pop_q32 (B, P)).

    Every operation is a u64 shift/multiply/add with no intermediate over
    2⁶⁴, mirrored exactly by the jitted kernel in ``ops/devicegen.py``.
    """
    ref_thresh = _U64(math.ceil(ref_block_fraction * 2.0**53))
    is_ref_block = (_u64(site_key, positions, _S_REF_BLOCK) >> _U64(11)) < ref_thresh
    u_af = _u64(site_key, positions, _S_AF) >> _U64(48)  # Q16
    u2 = u_af * u_af  # Q32, fits 32 bits
    af_q32 = _U64(_AF_BASE_Q32) + ((u2 * _U64(_AF_SPAN_Q16)) >> _U64(16))
    pops = []
    for p in range(n_pops):
        u_p = _u64(site_key, positions, _S_POP_BASE + p) >> _U64(48)  # Q16
        factor_q16 = _U64(_POP_BASE_Q16) + ((u_p * _U64(_POP_SPAN_Q17)) >> _U64(16))
        af_pop = (af_q32 * factor_q16) >> _U64(16)
        pops.append(np.clip(af_pop, _U64(_POP_LO_Q32), _U64(_POP_HI_Q32)))
    return is_ref_block, af_q32, np.stack(pops, axis=1)


def _mix(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized over uint64 arrays (wrapping mod 2^64)."""
    with np.errstate(over="ignore"):
        x = (x + _P1).astype(_U64)
        x = ((x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)).astype(_U64)
        x = ((x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)).astype(_U64)
        return (x ^ (x >> _U64(31))).astype(_U64)


def _string_key(s: str) -> np.uint64:
    return _U64(int.from_bytes(murmur3_x64_128(s.encode("utf-8"))[:8], "little"))


def _u01(key: np.uint64, pos, stream: int, sample=0, allele=0) -> np.ndarray:
    """Deterministic uniform [0,1) draws keyed by all arguments.

    ``pos`` / ``sample`` / ``allele`` may be scalars or broadcastable arrays.
    """
    with np.errstate(over="ignore"):
        h = _mix(key ^ (np.asarray(pos, dtype=np.int64).astype(_U64) * _P2))
        h = _mix(h ^ (_U64(stream) * _P3))
        h = _mix(h ^ (np.asarray(sample, dtype=np.int64).astype(_U64) * _P4))
        h = _mix(h ^ (np.asarray(allele, dtype=np.int64).astype(_U64) * _P1))
    return (h >> _U64(11)).astype(np.float64) * (2.0**-53)


def _u64(key: np.uint64, pos, stream: int, sample=0, allele=0) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = _mix(key ^ (np.asarray(pos, dtype=np.int64).astype(_U64) * _P2))
        h = _mix(h ^ (_U64(stream) * _P3))
        h = _mix(h ^ (np.asarray(sample, dtype=np.int64).astype(_U64) * _P4))
        h = _mix(h ^ (np.asarray(allele, dtype=np.int64).astype(_U64) * _P1))
    return h


# ---- the genotype draw stream (the hot path) -------------------------------
#
# The genotype data plane is the only stream drawn per (site, sample) — at
# whole-genome scale that is ~10¹¹ draws, and its hash cost bounds ingest
# throughput (see DESIGN.md "single-chip ingest roofline"). It therefore uses
# a cheaper construction than the general-purpose ``_u64`` stream: the 64-bit
# per-site state ``h₂`` (same splitmix64 prefix as ``_u64`` with
# ``stream=_S_GENOTYPE``) is xor-combined with the sample term and FOLDED to
# 32 bits, then finalized with ONE murmur3 fmix32 — 1 u64 xor + 2 u32
# multiplies per (site, sample) instead of three full splitmix64 rounds
# (6 u64 multiplies, each ~3 u32 multiplies once XLA emulates u64 on TPU).
# The second allele's draw is a multiplicative re-mix of the first (one more
# u32 multiply). Folding AFTER the sample xor keeps the pre-fold state
# unique per (site, sample): fold collisions are isolated scalar
# coincidences (~2⁻³² per pair), never whole shared genotype rows.
# Allele draws compare directly against the Q32 integer thresholds
# (``draw32 < af_pop_q32`` ⟺ ``draw32·2⁻³² < af_pop``) — the device kernel
# (``ops/devicegen.py``) reproduces this bit for bit.

_GOLD32 = np.uint32(0x9E3779B9)
_FMIX_C1 = np.uint32(0x85EBCA6B)
_FMIX_C2 = np.uint32(0xC2B2AE35)


def _fmix32(x: np.ndarray) -> np.ndarray:
    """murmur3 fmix32 finalizer, vectorized over uint32 (wrapping mod 2^32)."""
    with np.errstate(over="ignore"):
        x = ((x ^ (x >> np.uint32(16))) * _FMIX_C1).astype(np.uint32)
        x = ((x ^ (x >> np.uint32(13))) * _FMIX_C2).astype(np.uint32)
        return (x ^ (x >> np.uint32(16))).astype(np.uint32)


def _genotype_draw_pair(
    vs_key: np.uint64, positions: np.ndarray, num_samples: int
) -> "tuple[np.ndarray, np.ndarray]":
    """The two (B, N) uint32 allele draws of the genotype stream."""
    with np.errstate(over="ignore"):
        h1 = _mix(
            vs_key ^ (np.asarray(positions, dtype=np.int64).astype(_U64) * _P2)
        )
        h2 = _mix(h1 ^ (_U64(_S_GENOTYPE) * _P3))
        samples = np.arange(num_samples, dtype=np.int64).astype(_U64) * _P4
        x64 = h2[:, None] ^ samples[None, :]
        x32 = ((x64 >> _U64(32)) ^ x64).astype(np.uint32)
        d1 = _fmix32(x32)
        d2 = ((d1 * _GOLD32) ^ _FMIX_C1).astype(np.uint32)
    return d1, d2


#: Default candidate-site grid density: one site every N bases (~1/100
#: approximates 1KG phase 1's ~39M sites over ~2.9 Gb). ONE constant shared
#: by the source default below and the device-free plan validator's static
#: site-count bound (``check/plan.py``'s exactness-window facts).
DEFAULT_VARIANT_SPACING = 100


class SyntheticGenomicsSource(GenomicsSource):
    """A deterministic cohort with population structure.

    Args:
        num_samples: cohort size per variant set (1KG phase 1: 2,504).
        seed: base seed; all draws derive from it.
        variant_spacing: one candidate variant site every N bases (~1/100
            approximates 1KG phase 1's ~39M sites over ~2.9 Gb).
        ref_block_fraction: fraction of sites that are reference-matching
            blocks (``referenceBases == "N"``, no alternates — the record
            class the Klotho/BRCA1 examples count).
        n_pops: number of synthetic populations.
        read_length / read_depth: synthetic read geometry for the reads API.
        cohort_sizes: optional per-variant-set cohort sizes (variant set id →
            sample count); sets not listed use ``num_samples``. This is how
            the reference's ACTUAL joint-cohort scenario is modeled — e.g.
            1000 Genomes (2,504 samples) joined with Platinum Genomes (~17
            deep genomes) (``VariantsPca.scala:155-168``;
            ``SearchVariantsExample.scala:28``).
    """

    def __init__(
        self,
        num_samples: int = 2504,
        seed: int = 42,
        variant_spacing: int = DEFAULT_VARIANT_SPACING,
        ref_block_fraction: float = 0.1,
        n_pops: int = 4,
        read_length: int = 100,
        read_depth: int = 8,
        somatic_rate: float = 0.002,
        cohort_sizes: Optional[Mapping[str, int]] = None,
    ):
        self.num_samples = int(num_samples)
        self.seed = int(seed)
        self.variant_spacing = int(variant_spacing)
        self.ref_block_fraction = float(ref_block_fraction)
        self.n_pops = int(n_pops)
        self.read_length = int(read_length)
        self.read_depth = int(read_depth)
        self.somatic_rate = float(somatic_rate)
        self.cohort_sizes = {
            k: int(v) for k, v in (cohort_sizes or {}).items()
        }
        # Contiguous population blocks: sample s → pop s*n_pops//N.
        self._pops = self._pops_for_size(self.num_samples)

    def _pops_for_size(self, n: int) -> np.ndarray:
        return (np.arange(n, dtype=np.int64) * self.n_pops) // max(1, n)

    def num_samples_for(self, variant_set_id: str) -> int:
        """This variant set's cohort size (``cohort_sizes`` override or the
        default ``num_samples``)."""
        return self.cohort_sizes.get(variant_set_id, self.num_samples)

    def populations_for(self, variant_set_id: str) -> np.ndarray:
        """Sample → population for this variant set's cohort."""
        n = self.num_samples_for(variant_set_id)
        return self._pops if n == self.num_samples else self._pops_for_size(n)

    # ------------------------------------------------------------------ keys

    def _vs_key(self, variant_set_id: str) -> np.uint64:
        with np.errstate(over="ignore"):
            return _mix(_U64(self.seed) ^ _string_key(variant_set_id))

    def _rgs_key(self, read_group_set_id: str) -> np.uint64:
        with np.errstate(over="ignore"):
            return _mix(_U64(self.seed) ^ _string_key(read_group_set_id))

    # ------------------------------------------------------- driver metadata

    def callset_id(self, variant_set_id: str, i: int) -> str:
        """Callset ids follow the public-data convention ``<variantset>-<i>``;
        ``emitResult`` splits on '-' to recover the dataset id
        (``VariantsPca.scala:275``)."""
        return f"{variant_set_id}-{i}"

    def callset_name(self, variant_set_id: str, i: int) -> str:
        tag = int(self._vs_key(variant_set_id) % _U64(90))
        return f"S{tag:02d}N{i:05d}"

    def search_callsets(self, variant_set_ids: Sequence[str]) -> List[Dict]:
        """Callsets across the requested variant sets. Duplicate variant-set
        ids contribute their callsets once, as the real SearchCallSets API
        (a search over a *set* of variant sets) would
        (``VariantsPca.scala:97-105``)."""
        out = []
        seen = set()
        for vsid in variant_set_ids:
            if vsid in seen:
                continue
            seen.add(vsid)
            for i in range(self.num_samples_for(vsid)):
                out.append(
                    {"id": self.callset_id(vsid, i), "name": self.callset_name(vsid, i)}
                )
        return out

    def get_contigs(
        self,
        variant_set_id: str,
        sex_filter: SexChromosomeFilter = SexChromosomeFilter.INCLUDE_XY,
    ) -> List[Contig]:
        contigs = [
            Contig(name, 0, length)
            for name, length in Examples.HUMAN_CHROMOSOMES.items()
        ]
        return filter_sex_chromosomes(contigs, sex_filter)

    def client(self) -> "SyntheticClient":
        return SyntheticClient(self)

    # ------------------------------------------------------- variant payloads

    def _site_positions(self, start: int, end: int) -> np.ndarray:
        """Candidate variant sites on the global grid inside [start, end)."""
        spacing = self.variant_spacing
        first = ((max(start, 0) + spacing - 1) // spacing) * spacing
        if first >= end:
            return np.empty(0, dtype=np.int64)
        return np.arange(first, end, spacing, dtype=np.int64)

    def _site_fields(
        self, variant_set_id: str, positions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Per-site draws shared by both paths.

        Returns (is_ref_block, af, af_pop[B,P], ref_base_idx, alt_base_idx).
        Site identity (existence, ref/alt, base AF) is keyed by position only,
        NOT by variant set — so distinct variant sets share sites and their
        murmur3 variant keys match across datasets, exercising the
        join/merge paths the way 1KG + Platinum would
        (``VariantsPca.scala:155-188``).
        """
        site_key = _mix(_U64(self.seed))
        is_ref_block, af_q32, af_pop_q32 = _site_fields_q(
            site_key, positions, self.ref_block_fraction, self.n_pops
        )
        # Exact dyadic floats (k·2⁻³²): float comparisons downstream equal
        # the device kernel's integer compares bit for bit.
        af = af_q32.astype(np.float64) * 2.0**-32
        af_pop = af_pop_q32.astype(np.float64) * 2.0**-32
        ref_idx = (_u64(site_key, positions, _S_REF_BASE) % _U64(4)).astype(np.int64)
        alt_off = (_u64(site_key, positions, _S_ALT_BASE) % _U64(3)).astype(np.int64)
        alt_idx = (ref_idx + 1 + alt_off) % 4
        return is_ref_block, af, af_pop, ref_idx, alt_idx

    @property
    def site_key(self) -> int:
        """The uint64 key of the variant-set-independent site-metadata
        streams (``_site_fields``) — with :meth:`genotype_stream_key` and
        the grid, everything the device ingest kernel needs."""
        return int(_mix(_U64(self.seed)))

    def genotype_stream_key(self, variant_set_id: str) -> int:
        """The per-variant-set uint64 key of the genotype draw stream — the
        device generation path (``ops/devicegen.py``) reproduces
        :meth:`_genotype_alleles` bitwise from this key."""
        return int(self._vs_key(variant_set_id))

    @property
    def populations(self) -> np.ndarray:
        """Sample → population index (``(N,)`` int64)."""
        return self._pops

    def page_requests(self, contig: Contig, bases_per_partition: int) -> int:
        """Wire-equivalent request count for scanning ``contig`` in
        ``bases_per_partition`` windows: one request per
        ``VARIANTS_PAGE_SIZE``-site page per shard, at least one per shard —
        the same accounting ``SyntheticClient.search_variants`` performs."""
        total = 0
        for shard in contig.get_shards(bases_per_partition):
            k0, k1 = self.site_grid_range(shard)
            total += max(1, -(-(k1 - k0) // VARIANTS_PAGE_SIZE))
        return total

    def declared_sites(self, contig: Contig) -> int:
        """Exact candidate-site weight of ``contig`` for the host →
        contig-partition split: the site-grid span itself — the synthetic
        grid is declared geometry, so the split balances on the TRUE site
        counts (base sources fall back to the base-range prior)."""
        k0, k1 = self.site_grid_range(contig)
        return k1 - k0

    def site_grid_range(self, contig: Contig) -> Tuple[int, int]:
        """The contig's candidate-site grid as index range ``[k0, k1)`` with
        position ``k · variant_spacing`` — the only ingest metadata the
        device generation path needs (``ops/devicegen.py`` recomputes
        everything else on device)."""
        spacing = self.variant_spacing
        k0 = -(-max(contig.start, 0) // spacing)
        k1 = -(-contig.end // spacing)
        return k0, max(k0, k1)

    def site_threshold_plan(
        self,
        contig: Contig,
        min_allele_frequency: Optional[float] = None,
        chunk_sites: int = 1 << 20,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Host half of the device-generation path: per-site integer
        comparison thresholds for kept sites.

        Yields dense ``(positions (B,), thresholds (B, n_pops) uint64)``
        batches where ``thresholds[:, p] = af_pop_q32[:, p]`` — the Q32
        integer thresholds the genotype draws compare against
        (``draw32 < af_pop_q32``, see ``_genotype_draw_pair`` and
        ``ops/devicegen.py``). Ref-block sites and AF-filtered sites are
        compacted out, mirroring :meth:`genotype_blocks`' drop semantics.
        """
        all_positions = self._site_positions(contig.start, contig.end)
        self.plan_sites_scanned = getattr(self, "plan_sites_scanned", 0)
        for off in range(0, len(all_positions), chunk_sites):
            positions = all_positions[off : off + chunk_sites]
            is_ref_block, af, af_pop, _, _ = self._site_fields("", positions)
            keep = ~is_ref_block
            if min_allele_frequency is not None:
                keep &= af_passes(af, min_allele_frequency)
            self.plan_sites_scanned += len(positions)
            positions = positions[keep]
            if len(positions) == 0:
                continue
            # af_pop is the exact dyadic k·2⁻³², so ·2³² recovers k exactly.
            thresholds = np.round(af_pop[keep] * (2.0**32)).astype(np.uint64)
            yield positions, thresholds

    def _genotype_alleles(
        self, variant_set_id: str, positions: np.ndarray
    ) -> np.ndarray:
        """(B, N, 2) {0,1} allele draws; genotypes are per variant set
        (different datasets = different individuals at shared sites), with
        N this set's cohort size (``cohort_sizes``). Integer Q32 compares of
        the genotype draw stream (``_genotype_draw_pair``) against the
        per-population thresholds — bit-identical to the device kernel."""
        vs_key = self._vs_key(variant_set_id)
        site_key = _mix(_U64(self.seed))
        _, _, af_pop_q32 = _site_fields_q(
            site_key, positions, self.ref_block_fraction, self.n_pops
        )
        n = self.num_samples_for(variant_set_id)
        pops = self.populations_for(variant_set_id)
        # Q32 thresholds are < 2^32 by construction (clipped at _POP_HI_Q32).
        k = af_pop_q32[:, pops].astype(np.uint32)  # (B, N)
        d1, d2 = _genotype_draw_pair(vs_key, positions, n)
        return np.stack([d1 < k, d2 < k], axis=2).astype(np.int8)

    def genotype_blocks(
        self,
        variant_set_id: str,
        contig: Contig,
        block_size: int = 1024,
        min_allele_frequency: Optional[float] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Packed fast path: dense has-variation blocks for the Gramian.

        Yields dicts with ``positions`` (B,), ``has_variation`` uint8 (B, N),
        ``af`` (B,). Reference-block sites are all-zero rows (no call has
        variation) and are dropped, matching the ``filter(_.size > 0)`` stage
        (``VariantsPca.scala:206``). ``min_allele_frequency`` applies the
        ``--min-allele-frequency`` filter (``VariantsPca.scala:136-148``,
        strictly greater, on the site's AF info value).
        """
        all_positions = self._site_positions(contig.start, contig.end)
        for off in range(0, len(all_positions), block_size):
            positions = all_positions[off : off + block_size]
            is_ref_block, af, _, _, _ = self._site_fields(variant_set_id, positions)
            keep = ~is_ref_block
            if min_allele_frequency is not None:
                keep &= af_passes(af, min_allele_frequency)
            positions = positions[keep]
            af = af[keep]
            if len(positions) == 0:
                continue
            alleles = self._genotype_alleles(variant_set_id, positions)
            has_variation = (alleles.max(axis=2) > 0).astype(np.uint8)
            nonzero = has_variation.any(axis=1)
            yield {
                "positions": positions[nonzero],
                "has_variation": has_variation[nonzero],
                "af": af[nonzero],
            }

    def variant_json(self, variant_set_id: str, contig_name: str, pos: int) -> Dict:
        """One wire-format variant record (the JSON the reference's Java
        client would deserialize, ``rdd/VariantsRDD.scala:98-149``)."""
        positions = np.array([pos], dtype=np.int64)
        is_ref_block, af, _, ref_idx, alt_idx = self._site_fields(
            variant_set_id, positions
        )
        record: Dict = {
            "id": f"{variant_set_id}:{contig_name}:{pos}",
            "variantSetId": variant_set_id,
            "referenceName": contig_name,
            "start": int(pos),
            "created": 0,
        }
        if bool(is_ref_block[0]):
            record["end"] = int(pos) + self.variant_spacing
            record["referenceBases"] = "N"
            genotypes = np.zeros(
                (1, self.num_samples_for(variant_set_id), 2), dtype=np.int8
            )
        else:
            record["end"] = int(pos) + 1
            record["referenceBases"] = _BASES[int(ref_idx[0])]
            record["alternateBases"] = [_BASES[int(alt_idx[0])]]
            record["info"] = {"AF": [f"{float(_af6(af)[0]):.6f}"]}
            genotypes = self._genotype_alleles(variant_set_id, positions)
        record["calls"] = [
            {
                "callSetId": self.callset_id(variant_set_id, s),
                "callSetName": self.callset_name(variant_set_id, s),
                "genotype": [int(genotypes[0, s, 0]), int(genotypes[0, s, 1])],
                "phaseset": "*",
            }
            for s in range(self.num_samples_for(variant_set_id))
        ]
        return record

    # --------------------------------------------------------- read payloads

    def _germline_base(self, contig_name: str, positions: np.ndarray) -> np.ndarray:
        key = _mix(_U64(self.seed) ^ _string_key(contig_name))
        return (_u64(key, positions, _S_GERMLINE_BASE) % _U64(4)).astype(np.int64)

    def _is_somatic_site(self, contig_name: str, positions: np.ndarray) -> np.ndarray:
        key = _mix(_U64(self.seed) ^ _string_key(contig_name))
        return _u01(key, positions, _S_SOMATIC) < self.somatic_rate

    def read_json(
        self, read_group_set_id: str, contig_name: str, start: int, tile: int
    ) -> Dict:
        """One wire-format read.

        The read's bases follow the deterministic germline reference of
        ``contig_name``; read group sets whose id contains ``"Tumor"`` (or the
        DREAM tumor id) additionally carry somatic alternates at hash-selected
        sites with ~50% variant allele fraction — giving SearchReadsExample4's
        tumor/normal comparison a real signal.
        """
        rgs_key = self._rgs_key(read_group_set_id)
        L = self.read_length
        positions = np.arange(start, start + L, dtype=np.int64)
        base_idx = self._germline_base(contig_name, positions)
        is_tumor = (
            "Tumor" in read_group_set_id
            or read_group_set_id == Examples.GOOGLE_DREAM_SET3_TUMOR
        )
        if is_tumor:
            somatic = self._is_somatic_site(contig_name, positions)
            carries_alt = (
                _u01(rgs_key, positions, _S_READ_ALLELE, sample=start, allele=tile)
                < 0.5
            )
            flip = somatic & carries_alt
            base_idx = np.where(flip, (base_idx + 1) % 4, base_idx)
        sequence = "".join(_BASES[i] for i in base_idx)
        qual = (
            20
            + (
                _u64(rgs_key, positions, _S_READ_BASEQ, sample=start, allele=tile)
                % _U64(21)
            ).astype(np.int64)
        )
        mapq = int(
            20
            + int(
                _u64(rgs_key, np.int64(start), _S_READ_MAPQ, allele=tile) % _U64(41)
            )
        )
        return {
            "id": f"{read_group_set_id}:{contig_name}:{start}:{tile}",
            "fragmentName": f"frag-{contig_name}-{start}-{tile}",
            "readGroupSetId": read_group_set_id,
            "alignedSequence": sequence,
            "alignedQuality": [int(q) for q in qual],
            "fragmentLength": 300,
            "alignment": {
                "position": {"referenceName": contig_name, "position": int(start)},
                "mappingQuality": mapq,
                "cigar": [
                    {"operationLength": L, "operation": "ALIGNMENT_MATCH"}
                ],
            },
        }

    def read_starts(self, start: int, end: int) -> Iterator[Tuple[int, int]]:
        """(position, tile) pairs of reads starting in [start, end).

        Reads are laid out as ``read_depth`` staggered full tilings of length
        ``read_length``: tile j starts at offsets ≡ j*(L//depth) (mod L), so
        per-base depth is uniformly ``read_depth``.
        """
        L = self.read_length
        step = max(1, L // self.read_depth)
        for tile in range(self.read_depth):
            offset = tile * step
            first = ((max(start - offset, 0) + L - 1) // L) * L + offset
            for pos in range(first, end, L):
                if pos >= start:
                    yield pos, tile


class SyntheticClient(GenomicsClient):
    """A per-partition session over the synthetic source, with the page
    accounting of the reference's ``Paginator`` (one initialized request per
    page, ``rdd/VariantsRDD.scala:212-224``).

    Stream contract (``sources/stream.py``): records are GENERATED one at
    a time from the site grid — no file handle, no decoded payload larger
    than one record ever stages on host — so the synthetic arm of the
    hostmem totality proof carries no wire-table term at all; its page
    windows exist only for request accounting parity with the REST arm."""

    def __init__(self, source: SyntheticGenomicsSource):
        super().__init__()
        self.source = source

    def search_variants(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = VARIANTS_PAGE_SIZE,
    ) -> Iterator[Dict]:
        src = self.source
        variant_set_id = request["variantSetIds"][0]
        contig_name = request["referenceName"]
        start, end = int(request["start"]), int(request["end"])
        # Candidate sites, including one spacing of lookback for records that
        # overlap the range start (reference-matching blocks have extent).
        candidates = src._site_positions(start - src.variant_spacing, end)
        emitted = 0
        for pos in candidates:
            pos = int(pos)
            if boundary is ShardBoundary.STRICT:
                if not (start <= pos < end):
                    continue
            else:  # OVERLAPS
                site_end = pos + src.variant_spacing  # max extent (ref blocks)
                if site_end <= start or pos >= end:
                    continue
            if emitted % page_size == 0:
                self.counters.add_request()
            emitted += 1
            yield src.variant_json(variant_set_id, contig_name, pos)
        if emitted == 0:
            # Even an empty shard costs one request.
            self.counters.add_request()

    def search_reads(
        self,
        request: Mapping,
        boundary: ShardBoundary = ShardBoundary.STRICT,
        page_size: int = 256,
    ) -> Iterator[Dict]:
        src = self.source
        contig_name = request["referenceName"]
        start, end = int(request["start"]), int(request["end"])
        emitted = 0
        # STRICT: only reads STARTING in [start, end) — each read belongs to
        # exactly one shard. OVERLAPS: also reads starting before the range
        # whose alignment extends into it (the API's overlap semantics).
        scan_start = (
            start
            if boundary is ShardBoundary.STRICT
            else max(0, start - src.read_length)
        )
        for read_group_set_id in request["readGroupSetIds"]:
            for pos, tile in src.read_starts(scan_start, end):
                if boundary is ShardBoundary.OVERLAPS and pos + src.read_length <= start:
                    continue
                if emitted % page_size == 0:
                    self.counters.add_request()
                emitted += 1
                yield src.read_json(read_group_set_id, contig_name, pos, tile)
        if emitted == 0:
            self.counters.add_request()


__all__ = [
    "DEFAULT_VARIANT_SPACING",
    "SyntheticGenomicsSource",
    "SyntheticClient",
]

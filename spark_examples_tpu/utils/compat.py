"""JAX version compatibility shims.

One import site for APIs that moved between jax releases, so every ops
module keys off the same resolution instead of pinning a jax version the
image may not have.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace in jax 0.6; this repo targets both (the seed image ships
0.4.37, where only the experimental path exists).
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4/0.5
    from jax.experimental.shard_map import (  # type: ignore[no-redef]
        shard_map as _shard_map,
    )

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:

    def shard_map(*args, **kwargs):
        """0.4's shard_map with the modern ``check_vma`` kwarg translated to
        its old name ``check_rep`` (same semantics: skip the per-output
        replication/varying-axes check)."""
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

try:  # newer jax ships lax.axis_size
    from jax.lax import axis_size  # type: ignore[attr-defined]
except ImportError:  # jax 0.4

    def axis_size(axis_name):  # type: ignore[no-redef]
        """Static size of a named mesh axis inside shard_map: ``psum`` of a
        Python literal constant-folds to a concrete int at trace time (the
        long-standing jax idiom), so callers can drive ``range``/``fori_loop``
        bounds with it exactly like the modern ``lax.axis_size``."""
        from jax import lax

        return lax.psum(1, axis_name)


try:  # newer jax: top-level context manager
    from jax import enable_x64  # type: ignore[attr-defined]
except ImportError:  # jax 0.4: experimental only
    from jax.experimental import enable_x64  # type: ignore[no-redef]

    # Heal the modern spelling for every call site (ops, pipeline, tests all
    # write ``with jax.enable_x64(True)``): one alias here instead of a
    # version guard at ~30 sites. Loaded from the package __init__, so the
    # alias exists before any module body that uses it runs.
    jax.enable_x64 = enable_x64

__all__ = ["axis_size", "enable_x64", "shard_map"]

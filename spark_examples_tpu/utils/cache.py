"""Shared persistent XLA compile cache configuration + warm-geometry ledger.

First TPU compile of a shape costs tens of seconds; the CLI and the
benchmark reuse one cache location (outside the repo, so compile artifacts
never enter git — a 152 MB lesson from round 1).

The warm-geometry ledger is the resident service's half of the story
(``serve/``): a process-wide record of every analysis geometry this
process has already run. The fingerprint covers exactly the flags that
shape compiled programs (cohort width, block size, mesh, strategy, dtype
ladder, ingest path), so a repeated geometry inside one process — the
compile-once promise of the daemon — is a *hit* and a fresh geometry is a
*miss*. The counters are exported as well-known gauges
(``obs/metrics.py``), sampled by the heartbeat, and recorded in the run
manifest's ``compile_cache`` block: warm-vs-cold is observable, not
inferred from wall-clock.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional, Set, Tuple


def enable_persistent_compile_cache(cache_dir: Optional[str] = None) -> None:
    """Point XLA's persistent compilation cache at ``cache_dir`` (the
    resident daemon keys it under its run directory, so a restarted daemon
    reloads the previous incarnation's compile artifacts instead of paying
    the ~9.5 s whole-genome recompile) or, by default, the shared
    per-user location the CLI and the benchmark use.

    The default location is a write OUTSIDE the working tree, so
    ``SPARK_EXAMPLES_TPU_NO_CACHE=1`` (test/CI hygiene) disables it; an
    EXPLICIT ``cache_dir`` is caller-owned placement (the daemon's run
    dir, a test's tmp dir) and is honored regardless. An explicit dir
    also persists EVERY compile (min-compile-time 0): the daemon's
    geometry ledger claims "warm" for every fingerprint it primes, which
    is only honest if sub-second compiles left artifacts too — the
    shared default location keeps the 1 s floor so ad-hoc CLI runs don't
    churn it with trivia. Never raises."""
    min_compile_seconds = 0.0 if cache_dir is not None else 1.0
    if cache_dir is None:
        if os.environ.get("SPARK_EXAMPLES_TPU_NO_CACHE") == "1":
            return
        cache_dir = os.path.join(
            os.path.expanduser("~/.cache"), "spark_examples_tpu", "jax_cache"
        )
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_seconds
        )
    except Exception as e:  # never block the caller on cache configuration
        import sys

        print(
            f"warning: persistent compile cache disabled ({e})",
            file=sys.stderr,
        )


# ---------------------------------------------------------------------------
# Warm-geometry ledger (process-wide; the serve/ executor's cache key).
# ---------------------------------------------------------------------------

#: Conf fields that do NOT shape compiled programs: output/telemetry
#: placement, credentials, and the robustness flags (checkpoint placement,
#: resume source, fault plans change WHEN work runs, never what the
#: compiled kernels compute — and the Gramian-checkpoint fingerprint
#: (``pipeline/checkpoint.py``) requires the saving and the resuming run
#: to digest identically despite differing in exactly these flags).
#: Everything else (cohort, block size, mesh, strategy, dtype flags,
#: ingest path, references, input files) is part of the geometry —
#: conservative on purpose: a fingerprint hit promises the in-process jit
#: caches are warm for every kernel this run dispatches.
_NON_GEOMETRY_FIELDS = frozenset(
    {
        "output_path",
        "metrics_json",
        "heartbeat_seconds",
        "profile_dir",
        "client_secrets",
        "spark_master",
        "gramian_checkpoint_dir",
        "checkpoint_every_sites",
        "resume_from",
        "fault_plan",
        # The analyses' output placements: pure artifact paths, no effect
        # on compiled programs — the fingerprint stays placement-invariant
        # (same contract as output_path/metrics_json above).
        "grm_out",
        "ld_out",
        "assoc_out",
        # The plan validator's stacked-group knob (`graftcheck plan
        # --fused-jobs K`): it sizes the ADMISSION question, not the
        # per-job program — a job's compile geometry is the same whether
        # it later rides a fused group or runs serially (the group's own
        # geometry is keyed by fused_group_fingerprint).
        "fused_jobs",
    }
)

#: Conf fields that select WHICH contig windows stream through the
#: compiled programs without changing the programs themselves: blocks are
#: shaped by (block_size, cohort width), not by the region list. Excluded
#: from :func:`batch_compile_fingerprint` (the continuous-batching
#: compatibility key) ON TOP of the non-geometry fields — two small-region
#: queries over different windows of the same cohort dispatch through the
#: same warm kernels and may coalesce into one dispatch group.
_REGION_FIELDS = frozenset({"references", "all_references"})

# lock order: geometry-ledger lock is a leaf — nothing else is acquired
# while holding it (machine-checked by `graftcheck lockgraph`).
_geometry_lock = threading.Lock()
_seen_geometries: Set[str] = set()
_geometry_hits = 0
_geometry_misses = 0
_ledger_path: Optional[str] = None


def _fingerprint_doc(conf, kind: str, exclude: frozenset) -> str:
    fields = getattr(conf, "__dataclass_fields__", None)
    if fields is not None:
        doc = {
            name: getattr(conf, name)
            for name in sorted(fields)
            if name not in exclude
        }
    else:  # mapping-shaped confs (tests)
        doc = {
            k: v for k, v in sorted(dict(conf).items()) if k not in exclude
        }
    doc["__kind__"] = kind
    blob = json.dumps(doc, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def compile_fingerprint(conf, kind: str = "pca") -> str:
    """Stable digest of one analysis geometry: every conf field except the
    output/telemetry placement flags, canonically serialized. ``kind``
    ("pca" | "similarity") is part of the geometry — a similarity-only run
    never compiles the center/eigh kernels, so it must not pre-warm the
    PCA fingerprint. Two equal fingerprints compile (and dispatch)
    identical programs."""
    return _fingerprint_doc(conf, kind, _NON_GEOMETRY_FIELDS)


def batch_compile_fingerprint(conf, kind: str = "pca") -> str:
    """The continuous-batching compatibility key (``serve/queue.py``):
    :func:`compile_fingerprint` made region-invariant. Two requests with
    equal batch fingerprints differ at most in WHICH contig windows they
    scan — same cohort width, block size, mesh, strategy, dtype ladder,
    ingest path — so they dispatch through the same compiled kernels and
    can safely ride one dispatch group back to back. Strictly coarser
    than the compile fingerprint, never coarser than the admission
    class."""
    return _fingerprint_doc(
        conf, kind, _NON_GEOMETRY_FIELDS | _REGION_FIELDS
    )


def fused_group_fingerprint(batch_fingerprint: str, num_jobs: int) -> str:
    """The fused batch group's OWN compile geometry: a K-lane stacked
    program (``ops/batched.py``) traces ``(K, N, N)`` shapes no serial
    member ever compiles, so warm-vs-cold attribution for fused dispatch
    is keyed by (shared batch fingerprint, jobs-axis size) — a repeat
    group of the same shape and size rides warm stacked kernels, a new K
    is honestly a miss even when every member geometry is warm."""
    blob = f"fused:{batch_fingerprint}:{int(num_jobs)}"
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def geometry_seen(key: str) -> bool:
    """Has this process already run (and therefore compiled) ``key``?
    Read-only: no counter moves, no ledger mutation."""
    with _geometry_lock:
        return key in _seen_geometries


def record_geometry(key: str) -> bool:
    """Record one run of geometry ``key``; returns ``True`` when the
    geometry was already warm (hit) and ``False`` on first sight (miss).
    The hit/miss counters move exactly once per call. With a persistent
    ledger attached (:func:`attach_geometry_ledger`), a first-sight key is
    appended to the ledger file so the NEXT process primes it back."""
    global _geometry_hits, _geometry_misses
    with _geometry_lock:
        if key in _seen_geometries:
            _geometry_hits += 1
            return True
        _seen_geometries.add(key)
        _geometry_misses += 1
        ledger = _ledger_path
    # Outside the leaf lock: an fsync'd file append must never extend the
    # ledger lock's hold time (O_APPEND keeps concurrent writers whole).
    if ledger is not None:
        try:
            with open(ledger, "a", encoding="utf-8") as f:
                f.write(key + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            import sys

            print(
                f"warning: geometry ledger append failed ({e}); the next "
                "daemon incarnation will see this geometry cold",
                file=sys.stderr,
            )
    return False


def attach_geometry_ledger(path: str) -> int:
    """Make the warm-geometry ledger survive process restarts: prime
    ``_seen_geometries`` from ``path`` (one fingerprint per line; a torn
    final line from a crashed append is skipped) and append every future
    first-sight geometry there. Returns the number of primed geometries.

    A primed fingerprint makes ``geometry_seen`` answer ``True`` in a
    process that never compiled it — that is the POINT: paired with the
    persistent XLA compilation cache keyed under the same run directory
    (``enable_persistent_compile_cache``), a repeat-geometry job after a
    daemon restart rebuilds its jit entries from disk artifacts instead of
    recompiling, so "warm" honestly means "no from-scratch compile", not
    only "in-process jit cache populated". Priming moves no hit/miss
    counters — those stay the lifetime record of THIS process's jobs."""
    global _ledger_path
    primed = 0
    keys = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                key = line.strip()
                # A fingerprint is exactly 16 hex chars; anything else is
                # a torn append from a killed writer — skip, don't raise.
                if len(key) == 16 and all(
                    c in "0123456789abcdef" for c in key
                ):
                    keys.append(key)
    except FileNotFoundError:
        pass
    with _geometry_lock:
        for key in keys:
            if key not in _seen_geometries:
                _seen_geometries.add(key)
                primed += 1
        _ledger_path = path
    return primed


def compile_cache_stats() -> Tuple[int, int]:
    """Process-wide ``(hits, misses)`` of the warm-geometry ledger."""
    with _geometry_lock:
        return _geometry_hits, _geometry_misses


def reset_compile_cache_stats() -> None:
    """Clear the ledger and counters (tests and bench isolation only —
    the daemon never resets: its counters are the service's lifetime
    warm-vs-cold record). Detaches any persistent ledger file too."""
    global _geometry_hits, _geometry_misses, _ledger_path
    with _geometry_lock:
        _seen_geometries.clear()
        _geometry_hits = 0
        _geometry_misses = 0
        _ledger_path = None


__all__ = [
    "enable_persistent_compile_cache",
    "compile_fingerprint",
    "batch_compile_fingerprint",
    "fused_group_fingerprint",
    "geometry_seen",
    "record_geometry",
    "attach_geometry_ledger",
    "compile_cache_stats",
    "reset_compile_cache_stats",
]

"""Shared persistent XLA compile cache configuration.

First TPU compile of a shape costs tens of seconds; the CLI and the
benchmark reuse one cache location (outside the repo, so compile artifacts
never enter git — a 152 MB lesson from round 1).
"""

from __future__ import annotations

import os


def enable_persistent_compile_cache() -> None:
    """No-op when SPARK_EXAMPLES_TPU_NO_CACHE=1 (test/CI hygiene: no writes
    outside the working tree); never raises."""
    if os.environ.get("SPARK_EXAMPLES_TPU_NO_CACHE") == "1":
        return
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.expanduser("~/.cache"), "spark_examples_tpu", "jax_cache"
            ),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # never block the caller on cache configuration
        import sys

        print(
            f"warning: persistent compile cache disabled ({e})",
            file=sys.stderr,
        )


__all__ = ["enable_persistent_compile_cache"]

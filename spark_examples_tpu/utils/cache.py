"""Shared persistent XLA compile cache configuration + warm-geometry ledger.

First TPU compile of a shape costs tens of seconds; the CLI and the
benchmark reuse one cache location (outside the repo, so compile artifacts
never enter git — a 152 MB lesson from round 1).

The warm-geometry ledger is the resident service's half of the story
(``serve/``): a process-wide record of every analysis geometry this
process has already run. The fingerprint covers exactly the flags that
shape compiled programs (cohort width, block size, mesh, strategy, dtype
ladder, ingest path), so a repeated geometry inside one process — the
compile-once promise of the daemon — is a *hit* and a fresh geometry is a
*miss*. The counters are exported as well-known gauges
(``obs/metrics.py``), sampled by the heartbeat, and recorded in the run
manifest's ``compile_cache`` block: warm-vs-cold is observable, not
inferred from wall-clock.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional, Set, Tuple


def enable_persistent_compile_cache() -> None:
    """No-op when SPARK_EXAMPLES_TPU_NO_CACHE=1 (test/CI hygiene: no writes
    outside the working tree); never raises."""
    if os.environ.get("SPARK_EXAMPLES_TPU_NO_CACHE") == "1":
        return
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.expanduser("~/.cache"), "spark_examples_tpu", "jax_cache"
            ),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # never block the caller on cache configuration
        import sys

        print(
            f"warning: persistent compile cache disabled ({e})",
            file=sys.stderr,
        )


# ---------------------------------------------------------------------------
# Warm-geometry ledger (process-wide; the serve/ executor's cache key).
# ---------------------------------------------------------------------------

#: Conf fields that do NOT shape compiled programs: output/telemetry
#: placement, credentials, and the robustness flags (checkpoint placement,
#: resume source, fault plans change WHEN work runs, never what the
#: compiled kernels compute — and the Gramian-checkpoint fingerprint
#: (``pipeline/checkpoint.py``) requires the saving and the resuming run
#: to digest identically despite differing in exactly these flags).
#: Everything else (cohort, block size, mesh, strategy, dtype flags,
#: ingest path, references, input files) is part of the geometry —
#: conservative on purpose: a fingerprint hit promises the in-process jit
#: caches are warm for every kernel this run dispatches.
_NON_GEOMETRY_FIELDS = frozenset(
    {
        "output_path",
        "metrics_json",
        "heartbeat_seconds",
        "profile_dir",
        "client_secrets",
        "spark_master",
        "gramian_checkpoint_dir",
        "checkpoint_every_sites",
        "resume_from",
        "fault_plan",
        # The analyses' output placements: pure artifact paths, no effect
        # on compiled programs — the fingerprint stays placement-invariant
        # (same contract as output_path/metrics_json above).
        "grm_out",
        "ld_out",
        "assoc_out",
    }
)

# lock order: geometry-ledger lock is a leaf — nothing else is acquired
# while holding it (machine-checked by `graftcheck lockgraph`).
_geometry_lock = threading.Lock()
_seen_geometries: Set[str] = set()
_geometry_hits = 0
_geometry_misses = 0


def compile_fingerprint(conf, kind: str = "pca") -> str:
    """Stable digest of one analysis geometry: every conf field except the
    output/telemetry placement flags, canonically serialized. ``kind``
    ("pca" | "similarity") is part of the geometry — a similarity-only run
    never compiles the center/eigh kernels, so it must not pre-warm the
    PCA fingerprint. Two equal fingerprints compile (and dispatch)
    identical programs."""
    fields = getattr(conf, "__dataclass_fields__", None)
    if fields is not None:
        doc = {
            name: getattr(conf, name)
            for name in sorted(fields)
            if name not in _NON_GEOMETRY_FIELDS
        }
    else:  # mapping-shaped confs (tests)
        doc = {
            k: v
            for k, v in sorted(dict(conf).items())
            if k not in _NON_GEOMETRY_FIELDS
        }
    doc["__kind__"] = kind
    blob = json.dumps(doc, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def geometry_seen(key: str) -> bool:
    """Has this process already run (and therefore compiled) ``key``?
    Read-only: no counter moves, no ledger mutation."""
    with _geometry_lock:
        return key in _seen_geometries


def record_geometry(key: str) -> bool:
    """Record one run of geometry ``key``; returns ``True`` when the
    geometry was already warm (hit) and ``False`` on first sight (miss).
    The hit/miss counters move exactly once per call."""
    global _geometry_hits, _geometry_misses
    with _geometry_lock:
        if key in _seen_geometries:
            _geometry_hits += 1
            return True
        _seen_geometries.add(key)
        _geometry_misses += 1
        return False


def compile_cache_stats() -> Tuple[int, int]:
    """Process-wide ``(hits, misses)`` of the warm-geometry ledger."""
    with _geometry_lock:
        return _geometry_hits, _geometry_misses


def reset_compile_cache_stats() -> None:
    """Clear the ledger and counters (tests and bench isolation only —
    the daemon never resets: its counters are the service's lifetime
    warm-vs-cold record)."""
    global _geometry_hits, _geometry_misses
    with _geometry_lock:
        _seen_geometries.clear()
        _geometry_hits = 0
        _geometry_misses = 0


__all__ = [
    "enable_persistent_compile_cache",
    "compile_fingerprint",
    "geometry_seen",
    "record_geometry",
    "compile_cache_stats",
    "reset_compile_cache_stats",
]

"""Shared persistent XLA compile cache configuration.

First TPU compile of a shape costs tens of seconds; the CLI and the
benchmark reuse one cache location (outside the repo, so compile artifacts
never enter git — a 152 MB lesson from round 1).
"""

from __future__ import annotations

import os


def enable_persistent_compile_cache() -> None:
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.expanduser("~/.cache"), "spark_examples_tpu", "jax_cache"
            ),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # never block the caller on cache configuration


__all__ = ["enable_persistent_compile_cache"]

"""MurmurHash3 x64 128-bit, matching Guava's ``Hashing.murmur3_128()``.

The reference keys variants by a Guava murmur3_128 of
contig / start / end / referenceBases / alternateBases
(``VariantsPca.scala:71-86``) and joins datasets on the resulting hex string.
Guava's ``HashCode.toString()`` is the lowercase hex of the digest bytes, which
for murmur3_128 are ``h1`` little-endian followed by ``h2`` little-endian; its
``Hasher.putString(s, UTF_8)`` appends UTF-8 bytes and ``putLong`` appends 8
little-endian bytes. We reproduce that byte protocol exactly so that variant
keys are stable and comparable with the reference's.
"""

_MASK = (1 << 64) - 1
_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK


def _fmix(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _MASK
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _MASK
    k ^= k >> 33
    return k


def murmur3_x64_128(data: bytes, seed: int = 0) -> bytes:
    """Digest bytes in Guava order: h1 little-endian then h2 little-endian."""
    length = len(data)
    h1 = seed
    h2 = seed
    nblocks = length // 16

    for i in range(nblocks):
        off = i * 16
        k1 = int.from_bytes(data[off : off + 8], "little")
        k2 = int.from_bytes(data[off + 8 : off + 16], "little")

        k1 = (k1 * _C1) & _MASK
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _MASK
        h1 ^= k1
        h1 = _rotl(h1, 27)
        h1 = (h1 + h2) & _MASK
        h1 = (h1 * 5 + 0x52DCE729) & _MASK

        k2 = (k2 * _C2) & _MASK
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _MASK
        h2 ^= k2
        h2 = _rotl(h2, 31)
        h2 = (h2 + h1) & _MASK
        h2 = (h2 * 5 + 0x38495AB5) & _MASK

    tail = data[nblocks * 16 :]
    k1 = 0
    k2 = 0
    tl = len(tail)
    if tl > 8:
        k2 = int.from_bytes(tail[8:], "little")
        k2 = (k2 * _C2) & _MASK
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _MASK
        h2 ^= k2
    if tl > 0:
        k1 = int.from_bytes(tail[:8], "little")
        k1 = (k1 * _C1) & _MASK
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _MASK
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _MASK
    h2 = (h2 + h1) & _MASK
    h1 = _fmix(h1)
    h2 = _fmix(h2)
    h1 = (h1 + h2) & _MASK
    h2 = (h2 + h1) & _MASK

    return h1.to_bytes(8, "little") + h2.to_bytes(8, "little")


def murmur3_x64_128_hex(data: bytes, seed: int = 0) -> str:
    """Lowercase hex digest, identical to Guava ``HashCode.toString()``."""
    return murmur3_x64_128(data, seed).hex()

"""Canonical allele-frequency and standardization arithmetic.

Two families of shared math live here — both are cross-path contracts,
declared once so no consumer can drift:

**Filter arithmetic.** The ``--min-allele-frequency`` comparison (strictly
greater, ``VariantsPca.scala:136-148``) must agree bit-for-bit across the
synthetic wire, packed and device ingest paths, whose AF values travel as
6-decimal strings or Q32 dyadic rationals. The canonical rule compares
micro-units: ``round(af · 1e6)  >  floor(threshold · 1e6)`` with the
threshold expanded over its exact binary value (via Fraction) — integer
comparisons sidestep the non-dyadic ``1e-6`` grid entirely. Generic (REST)
sources keep the reference's plain float comparison; these helpers are the
shared rule for paths that must match a device kernel.

**Standardization arithmetic.** The population-genetics analyses
(``analyses/``) derive per-site carrier counts and variance numerators
from the SAME has-variation rows the PCA Gramian accumulates:
:func:`carrier_counts` (``k = Σ x``, int64) and :func:`variance_counts`
(``k · (n − k) = n² · p·q``, kept in INTEGER form so GRM's VanRaden
denominator and LD's r² denominators are exact int64 arithmetic, never a
rounded ``p·q`` product — the implied frequency ``k / n`` lives in the
:data:`ops.contracts.ALLELE_FREQUENCY` [0, 1] contract, and counts
outside it fail loudly). Monomorphic sites (``k == 0`` or ``k == n``)
have zero variance; every consumer gets the zero-variance guard here
(denominator exactly 0, never NaN) instead of reinventing it. Ragged
tails (partial blocks) need no special casing — everything is vectorized
over whatever row count arrives.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def af_filter_micro(threshold: Optional[float]) -> Optional[int]:
    """``floor(threshold · 1e6)`` over the exact binary value of the
    threshold. ``None`` stays ``None`` (no filter)."""
    if threshold is None:
        return None
    from fractions import Fraction

    return int(Fraction(threshold) * 10**6 // 1)


def af_passes(af: np.ndarray, threshold: Optional[float]) -> np.ndarray:
    """Canonical micro-unit comparison. ``af`` may be the Q32 dyadic site AF
    or a value parsed back from the 6-decimal wire string — both round to
    the same integer (round-half-even, matching the device kernel)."""
    if threshold is None:
        return np.ones(np.shape(af), dtype=bool)
    micro = np.round(np.asarray(af, dtype=np.float64) * 1e6).astype(np.int64)
    return micro > af_filter_micro(threshold)


def carrier_counts(rows: np.ndarray) -> np.ndarray:
    """Per-site carrier counts ``k = Σ_s x[v, s]`` of a ``(B, N)``
    has-variation block (``ops/contracts.py:HAS_VARIATION`` {0,1} rows;
    count-valued join rows are out of contract for the analyses). int64 so
    downstream integer moments (``k²``, ``k·(n−k)``, ``n·C − k_i·k_j``)
    never wrap. Ragged tails are fine: B is whatever arrived."""
    rows = np.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(f"expected a (B, N) block, got shape {rows.shape}")
    return rows.astype(np.int64, copy=False).sum(axis=1)


def variance_counts(counts: np.ndarray, num_samples: int) -> np.ndarray:
    """Integer per-site variance numerator ``k · (n − k) = n² · p·q`` —
    exact int64, the shared denominator ingredient of GRM's VanRaden
    scaling and LD's r². Monomorphic sites (k == 0 or k == n) are exactly
    0, the zero-variance guard every consumer inherits. Counts outside
    [0, n] fail loudly: they mean a count-valued join row leaked into a
    {0,1} has-variation path (the frequency ``k / n`` would leave the
    ``ops/contracts.py:ALLELE_FREQUENCY`` [0, 1] range)."""
    n = int(num_samples)
    if n < 1:
        raise ValueError(f"num_samples must be >= 1, got {num_samples}")
    k = np.asarray(counts, dtype=np.int64)
    if k.size and (k.min() < 0 or k.max() > n):
        raise ValueError(
            f"carrier counts outside [0, {n}]: min {k.min()}, max {k.max()} "
            "(has-variation rows must be {0,1} membership bits)"
        )
    return k * (n - k)


__all__ = [
    "af_filter_micro",
    "af_passes",
    "carrier_counts",
    "variance_counts",
]

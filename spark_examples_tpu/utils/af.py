"""Canonical allele-frequency filter arithmetic.

The ``--min-allele-frequency`` comparison (strictly greater,
``VariantsPca.scala:136-148``) must agree bit-for-bit across the synthetic
wire, packed and device ingest paths, whose AF values travel as 6-decimal
strings or Q32 dyadic rationals. The canonical rule compares micro-units:
``round(af · 1e6)  >  floor(threshold · 1e6)`` with the threshold expanded
over its exact binary value (via Fraction) — integer comparisons sidestep
the non-dyadic ``1e-6`` grid entirely.

Generic (REST) sources keep the reference's plain float comparison; this
module is only the shared rule for paths that must match a device kernel.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def af_filter_micro(threshold: Optional[float]) -> Optional[int]:
    """``floor(threshold · 1e6)`` over the exact binary value of the
    threshold. ``None`` stays ``None`` (no filter)."""
    if threshold is None:
        return None
    from fractions import Fraction

    return int(Fraction(threshold) * 10**6 // 1)


def af_passes(af: np.ndarray, threshold: Optional[float]) -> np.ndarray:
    """Canonical micro-unit comparison. ``af`` may be the Q32 dyadic site AF
    or a value parsed back from the 6-decimal wire string — both round to
    the same integer (round-half-even, matching the device kernel)."""
    if threshold is None:
        return np.ones(np.shape(af), dtype=bool)
    micro = np.round(np.asarray(af, dtype=np.float64) * 1e6).astype(np.int64)
    return micro > af_filter_micro(threshold)


__all__ = ["af_filter_micro", "af_passes"]

from spark_examples_tpu.utils.murmur3 import murmur3_x64_128, murmur3_x64_128_hex

__all__ = ["murmur3_x64_128", "murmur3_x64_128_hex"]

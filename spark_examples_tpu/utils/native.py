"""On-demand build + ctypes bindings for the native (C++) components.

The compute path is JAX/XLA; the runtime AROUND it follows the reference in
using native code where the hot loop is host-side — here the VCF data-plane
parser (``native/vcfparse.cpp``) feeding the file source's packed ingest.
No pybind11 in this image, so the extension is a plain C-ABI shared object
compiled once with the system toolchain and loaded via ctypes; everything
degrades to the pure-Python implementations when no compiler is available
(``sources/files.py`` keeps the oracle).

The build is content-addressed: the .so lands in
``~/.cache/spark_examples_tpu/native/<sha of source+compiler>.so`` so source
edits rebuild and unchanged sources never recompile. With
``SPARK_EXAMPLES_TPU_NO_CACHE=1`` (test/CI hygiene) the artifact goes to a
process-lifetime temp directory instead.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Optional, Tuple

import numpy as np

_REPO_NATIVE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


class MalformedVcfLine(ValueError):
    """A malformed VCF data line. ``ordinal`` is the 1-based position among
    the DATA lines of the buffer (or span) that was being parsed — span
    parsers raise it span-relative, and the chunk-parallel merge
    (``sources/files.py``) translates it to the file-level ordinal so the
    error matches what the serial path reports for the same file."""

    def __init__(self, ordinal: int):
        super().__init__(f"malformed VCF data line #{int(ordinal)}")
        self.ordinal = int(ordinal)


def _compiler() -> Optional[str]:
    for name in ("g++", "clang++", "c++"):
        path = shutil.which(name)
        if path:
            return path
    return None


def _build_dir() -> str:
    if os.environ.get("SPARK_EXAMPLES_TPU_NO_CACHE") == "1":
        d = os.path.join(
            tempfile.gettempdir(), f"spark_examples_tpu_native_{os.getuid()}"
        )
    else:
        d = os.path.join(
            os.path.expanduser("~/.cache"), "spark_examples_tpu", "native"
        )
    os.makedirs(d, exist_ok=True)
    return d


#: Sanitizer build modes for the native layer (``graftcheck sanitize``).
#: Each maps to the compile/link flags of one instrumented build; -O1 keeps
#: stack traces honest where -O3 would inline them away. UBSan violations
#: are non-recoverable so a clean exit code MEANS clean.
SANITIZER_FLAGS = {
    "asan": ("-fsanitize=address", "-fno-omit-frame-pointer", "-g", "-O1"),
    "ubsan": (
        "-fsanitize=undefined",
        "-fno-sanitize-recover=undefined",
        "-g",
        "-O1",
    ),
    "tsan": ("-fsanitize=thread", "-g", "-O1"),
}


def _build(
    source_paths,
    flags: Tuple[str, ...] = ("-O3", "-shared", "-fPIC"),
    suffix: str = ".so",
) -> str:
    """Compile translation unit(s) to a content-addressed artifact; returns
    its path (reusing a previous identical build when present). The tag
    hashes sources + compiler + flags, so a sanitizer build and the release
    .so coexist in the cache and a flag change rebuilds."""
    if isinstance(source_paths, str):
        source_paths = [source_paths]
    compiler = _compiler()
    if compiler is None:
        raise RuntimeError("no C++ compiler on PATH")
    digest = hashlib.sha256()
    for path in source_paths:
        with open(path, "rb") as f:
            digest.update(f.read())
    digest.update(compiler.encode())
    digest.update(" ".join(flags).encode())
    digest.update(sys.version.encode())
    tag = digest.hexdigest()[:16]
    out = os.path.join(
        _build_dir(),
        f"{os.path.splitext(os.path.basename(source_paths[0]))[0]}"
        f"-{tag}{suffix}",
    )
    if os.path.exists(out):
        return out
    tmp = out + f".build-{os.getpid()}"
    cmd = [compiler, *flags, "-std=c++17", "-o", tmp, *source_paths]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def build_sanitizer_harness(mode: str) -> str:
    """Build the standalone sanitizer replay binary: ``vcfparse.cpp`` +
    ``native/sanitize_harness.cpp`` under one of :data:`SANITIZER_FLAGS`.

    A standalone executable rather than an instrumented .so: ASan/TSan
    require their runtime to be the FIRST thing in the process, which a
    ctypes ``dlopen`` into an uninstrumented CPython cannot guarantee
    (preload hacks disable the interceptors that matter). The binary also
    gives TSan a genuine multi-threaded replay of the span entry points —
    the same concurrency shape as the chunk-parallel ingest engine.
    Raises ``RuntimeError`` when no compiler is available (callers skip).
    """
    if mode not in SANITIZER_FLAGS:
        raise ValueError(
            f"unknown sanitizer mode {mode!r}; have {sorted(SANITIZER_FLAGS)}"
        )
    sources = [
        os.path.join(_REPO_NATIVE, "vcfparse.cpp"),
        os.path.join(_REPO_NATIVE, "sanitize_harness.cpp"),
    ]
    for path in sources:
        if not os.path.exists(path):
            raise RuntimeError(f"missing native source {path}")
    flags = SANITIZER_FLAGS[mode] + ("-pthread",)
    return _build(sources, flags=flags, suffix=f"-{mode}")


def vcf_library() -> Optional[ctypes.CDLL]:
    """The compiled VCF parser, or ``None`` (with the reason recorded) when
    it cannot be built — callers fall back to pure Python."""
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        path = _build(os.path.join(_REPO_NATIVE, "vcfparse.cpp"))
        # CDLL, never PyDLL: ctypes releases the GIL around CDLL foreign
        # calls, which is what lets the chunk-parallel ingest engine
        # (sources/files.py) run vcf_parse_span concurrently on a thread
        # pool. PyDLL would hold the GIL and serialize every worker
        # (tests/test_ingest_parallel.py pins the loader class).
        lib = ctypes.CDLL(path)
        lib.vcf_scan.restype = ctypes.c_int
        lib.vcf_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.vcf_parse.restype = ctypes.c_int64
        lib.vcf_parse.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        lib.vcf_count_data_lines.restype = ctypes.c_int64
        lib.vcf_count_data_lines.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.vcf_count_data_lines_span.restype = ctypes.c_int64
        lib.vcf_count_data_lines_span.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.vcf_parse_span.restype = ctypes.c_int64
        lib.vcf_parse_span.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        lib.vcf_scan_sites.restype = ctypes.c_int64
        lib.vcf_scan_sites.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        lib.vcf_mark_contig_changes.restype = None
        lib.vcf_mark_contig_changes.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
    except Exception as e:  # no compiler / build failure: fall back
        _lib_error = str(e)
        return None
    return _lib


def native_unavailable_reason() -> Optional[str]:
    vcf_library()
    return _lib_error


def parse_vcf_arrays(text: bytes) -> Optional[Tuple[np.ndarray, ...]]:
    """One native pass over decompressed VCF text.

    Returns ``(contigs (L,) object, positions (L,) i64, ends (L,) i64,
    af (L,) f64 — NaN where INFO has no AF, has_variation (L, N) i8)``, or
    ``None`` when the native library is unavailable. Raises ``ValueError``
    on malformed input (the Python parser raises too — parity includes the
    failure mode).
    """
    lib = vcf_library()
    if lib is None:
        return None
    n_lines = ctypes.c_int64()
    n_samples = ctypes.c_int64()
    # A headerless (sites-only) VCF scans as an empty cohort — the wire
    # parser's behavior; malformed data lines still raise from vcf_parse.
    lib.vcf_scan(
        text, len(text), ctypes.byref(n_lines), ctypes.byref(n_samples)
    )
    L, N = n_lines.value, n_samples.value
    positions = np.empty(L, dtype=np.int64)
    ends = np.empty(L, dtype=np.int64)
    af = np.empty(L, dtype=np.float64)
    has_variation = np.zeros((L, max(N, 1)), dtype=np.int8)
    contig_off = np.empty(L, dtype=np.int64)
    contig_len = np.empty(L, dtype=np.int64)
    parsed = lib.vcf_parse(
        text, len(text), N, positions, ends, af, has_variation,
        contig_off, contig_len,
    )
    if parsed < 0:
        raise MalformedVcfLine(-parsed)
    if parsed != L:
        raise ValueError(f"parsed {parsed} of {L} VCF data lines")
    contigs = np.empty(L, dtype=object)
    for i in range(L):
        contigs[i] = text[
            contig_off[i] : contig_off[i] + contig_len[i]
        ].decode("utf-8")
    return contigs, positions, ends, af, has_variation[:, :N]


def _contig_strings(text: bytes, contig_off, contig_len, rows: int):
    """Per-row contig names decoded run-wise: the native
    ``vcf_mark_contig_changes`` finds run boundaries in C (one memcmp per
    row), so the Python side decodes ONE string per run and ``np.repeat``s
    it — no per-row interpreter work on the streaming hot path. Falls back
    to a per-row loop when the library is unavailable (callers on the
    native path always have it)."""
    contigs = np.empty(rows, dtype=object)
    if rows == 0:
        return contigs
    lib = vcf_library()
    if lib is not None:
        flags = np.empty(rows, dtype=np.int8)
        lib.vcf_mark_contig_changes(text, contig_off, contig_len, rows, flags)
        starts = np.flatnonzero(flags)
        names = np.array(
            [
                text[contig_off[i] : contig_off[i] + contig_len[i]].decode(
                    "utf-8"
                )
                for i in starts
            ],
            dtype=object,
        )
        reps = np.diff(np.append(starts, rows))
        contigs[:] = np.repeat(names, reps)
        return contigs
    current_bytes: bytes = b""
    current_str = ""
    for i in range(rows):
        raw = text[contig_off[i] : contig_off[i] + contig_len[i]]
        if raw != current_bytes:
            current_bytes = raw
            current_str = raw.decode("utf-8")
        contigs[i] = current_str
    return contigs


def parse_vcf_chunk(text: bytes, n_samples: int):
    """Native parse of ONE streamed chunk (no #CHROM header needed: the
    caller learned ``n_samples`` from the header chunk; the chunk must end
    at a line boundary — the streaming reader carries partial lines).

    Returns the same array tuple as :func:`parse_vcf_arrays`, or ``None``
    when the native library is unavailable. Raises ``ValueError`` on a
    malformed data line (1-based ordinal WITHIN the chunk).
    """
    lib = vcf_library()
    if lib is None:
        return None
    L = int(lib.vcf_count_data_lines(text, len(text)))
    positions = np.empty(L, dtype=np.int64)
    ends = np.empty(L, dtype=np.int64)
    af = np.empty(L, dtype=np.float64)
    has_variation = np.zeros((L, max(n_samples, 1)), dtype=np.int8)
    contig_off = np.empty(L, dtype=np.int64)
    contig_len = np.empty(L, dtype=np.int64)
    parsed = lib.vcf_parse(
        text, len(text), n_samples, positions, ends, af, has_variation,
        contig_off, contig_len,
    )
    if parsed < 0:
        raise MalformedVcfLine(-parsed)
    if parsed != L:
        raise ValueError(f"parsed {parsed} of {L} VCF data lines")
    contigs = _contig_strings(text, contig_off, contig_len, L)
    return contigs, positions, ends, af, has_variation[:, :n_samples]


def scan_vcf_counts(text: bytes) -> Optional[Tuple[int, int]]:
    """One native header/line scan: ``(n_data_lines, n_samples)`` for the
    whole buffer (the serial pass the chunk-parallel parse shares with
    :func:`parse_vcf_arrays`, so both resolve the cohort identically —
    including the headerless and repeated-``#CHROM`` edge cases). ``None``
    when the native library is unavailable."""
    lib = vcf_library()
    if lib is None:
        return None
    n_lines = ctypes.c_int64()
    n_samples = ctypes.c_int64()
    lib.vcf_scan(
        text, len(text), ctypes.byref(n_lines), ctypes.byref(n_samples)
    )
    return n_lines.value, n_samples.value


def parse_vcf_span(text: bytes, begin: int, end: int, n_samples: int):
    """Native parse of ONE line-aligned span ``[begin, end)`` of ``text`` —
    the chunk-parallel worker body (``sources/files.py``). No bytes are
    copied: the span is addressed by offset into the shared buffer, and the
    two foreign calls (count + parse) both release the GIL, so N workers
    parse N spans on N cores concurrently.

    Returns the same array tuple as :func:`parse_vcf_chunk`, rows in span
    order. Raises ``ValueError`` on a malformed data line (1-based ordinal
    within the span). ``None`` when the native library is unavailable.
    """
    lib = vcf_library()
    if lib is None:
        return None
    begin, end = int(begin), int(end)
    if not 0 <= begin <= end <= len(text):
        raise ValueError(f"span [{begin}, {end}) outside text of {len(text)}")
    L = int(lib.vcf_count_data_lines_span(text, begin, end))
    positions = np.empty(L, dtype=np.int64)
    ends = np.empty(L, dtype=np.int64)
    af = np.empty(L, dtype=np.float64)
    has_variation = np.zeros((L, max(n_samples, 1)), dtype=np.int8)
    contig_off = np.empty(L, dtype=np.int64)
    contig_len = np.empty(L, dtype=np.int64)
    parsed = lib.vcf_parse_span(
        text, begin, end, n_samples, positions, ends, af, has_variation,
        contig_off, contig_len,
    )
    if parsed < 0:
        raise MalformedVcfLine(-parsed)
    if parsed != L:
        raise ValueError(f"parsed {parsed} of {L} VCF data lines")
    contigs = _contig_strings(text, contig_off, contig_len, L)
    return contigs, positions, ends, af, has_variation[:, :n_samples]


def scan_vcf_sites_chunk(text: bytes):
    """Native site-only scan of one streamed chunk: ``(contigs, positions,
    ends)`` without the per-sample genotype walk — the cheap pass behind
    lazy contig discovery. ``None`` when the native library is unavailable.
    """
    lib = vcf_library()
    if lib is None:
        return None
    L = int(lib.vcf_count_data_lines(text, len(text)))
    positions = np.empty(L, dtype=np.int64)
    ends = np.empty(L, dtype=np.int64)
    contig_off = np.empty(L, dtype=np.int64)
    contig_len = np.empty(L, dtype=np.int64)
    parsed = lib.vcf_scan_sites(
        text, len(text), positions, ends, contig_off, contig_len
    )
    if parsed < 0:
        raise MalformedVcfLine(-parsed)
    contigs = _contig_strings(text, contig_off, contig_len, L)
    return contigs, positions, ends


__all__ = [
    "MalformedVcfLine",
    "SANITIZER_FLAGS",
    "build_sanitizer_harness",
    "vcf_library",
    "native_unavailable_reason",
    "parse_vcf_arrays",
    "parse_vcf_chunk",
    "parse_vcf_span",
    "scan_vcf_counts",
    "scan_vcf_sites_chunk",
]

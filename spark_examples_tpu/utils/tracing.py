"""Tracing / profiling: the Spark-web-UI stand-in (SURVEY.md §5).

The reference delegated observability to the Spark UI (stage timelines on
ports 8080/4040, ``README.md:148-178``) and log4j. The TPU equivalents:

- :class:`StageTimes` — coarse per-stage wall-clock accounting for the
  driver pipeline (the moral equivalent of the Spark stage timeline),
  printed after the I/O stats report;
- :func:`device_trace` — a ``jax.profiler`` trace context producing a
  TensorBoard-loadable profile of the XLA ops (the fine-grained equivalent
  of drilling into a Spark stage), enabled by ``--profile-dir``.

Honest-timing note (remote-attached backends): dispatch is asynchronous and
``block_until_ready`` can ACK before execution completes, so a stage's wall
time is only meaningful when the stage ends in a synchronous fetch (the
driver's PCA stage does) or when ``sync=`` passes a device value to fetch.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, List, Optional, Tuple


class StageTimes:
    """Ordered per-stage wall-clock accounting."""

    def __init__(self) -> None:
        self.stages: List[Tuple[str, float]] = []

    @contextlib.contextmanager
    def stage(self, name: str, sync: Optional[Callable[[], object]] = None):
        """Time a stage; ``sync`` (if given) is called before closing the
        measurement to force outstanding device work to completion — pass a
        tiny fetch, e.g. ``lambda: jax.device_get(counter)``."""
        start = time.perf_counter()
        try:
            yield self
        finally:
            if sync is not None:
                sync()
            self.stages.append((name, time.perf_counter() - start))

    def as_dict(self) -> Dict[str, float]:
        return dict(self.stages)

    def __str__(self) -> str:
        lines = ["Stage timings:", "-------------------------------"]
        total = 0.0
        for name, seconds in self.stages:
            lines.append(f"{name}: {seconds:.3f} s")
            total += seconds
        lines.append(f"total: {total:.3f} s")
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(profile_dir: Optional[str]):
    """``jax.profiler.trace`` when a directory is given, no-op otherwise.

    The resulting trace loads in TensorBoard's profile plugin (or
    ``xprof``) and shows per-op device timelines — ingest kernels, MXU
    Gramian updates, collectives, and the eigensolve."""
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield


__all__ = ["StageTimes", "device_trace"]

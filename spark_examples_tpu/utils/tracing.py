"""Tracing / profiling: the Spark-web-UI stand-in (SURVEY.md §5).

The reference delegated observability to the Spark UI (stage timelines on
ports 8080/4040, ``README.md:148-178``) and log4j. The TPU equivalents:

- :class:`StageTimes` — coarse per-stage wall-clock accounting for the
  driver pipeline, now a thin shim over the hierarchical span recorder
  (``obs/spans.py``): every stage it times is also a span in the run
  manifest, while the printed report stays byte-identical;
- :func:`device_trace` — a ``jax.profiler`` trace context producing a
  TensorBoard-loadable profile of the XLA ops (the fine-grained equivalent
  of drilling into a Spark stage), enabled by ``--profile-dir``.

Honest-timing note (remote-attached backends): dispatch is asynchronous and
``block_until_ready`` can ACK before execution completes, so a stage's wall
time is only meaningful when the stage ends in a synchronous fetch (the
driver's PCA stage does) or when ``sync=`` passes a device value to fetch.
The span recorder carries this as the per-span ``synced`` flag.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Dict, List, Optional, Tuple

from spark_examples_tpu.obs.spans import SpanRecorder


class StageTimes:
    """Ordered per-stage wall-clock accounting, recorded as spans.

    ``recorder`` shares the run's :class:`SpanRecorder` (stages nest under
    whatever span is open, and deeper phases nest under the stages); a
    private recorder is created otherwise. ``stages`` keeps the historical
    ``[(name, seconds)]`` list so ``as_dict()`` and the printed report are
    unchanged.
    """

    def __init__(self, recorder: Optional[SpanRecorder] = None) -> None:
        self.recorder = recorder if recorder is not None else SpanRecorder()
        self.stages: List[Tuple[str, float]] = []

    @contextlib.contextmanager
    def stage(self, name: str, sync: Optional[Callable[[], object]] = None):
        """Time a stage; ``sync`` (if given) is called before closing the
        measurement to force outstanding device work to completion — pass a
        tiny fetch, e.g. ``lambda: jax.device_get(counter)``."""
        span = None
        try:
            with self.recorder.span(name, sync=sync) as span:
                yield self
        finally:
            if span is not None and span.seconds is not None:
                self.stages.append((name, span.seconds))

    def as_dict(self) -> Dict[str, float]:
        return dict(self.stages)

    def __str__(self) -> str:
        lines = ["Stage timings:", "-------------------------------"]
        total = 0.0
        for name, seconds in self.stages:
            lines.append(f"{name}: {seconds:.3f} s")
            total += seconds
        lines.append(f"total: {total:.3f} s")
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(profile_dir: Optional[str]):
    """``jax.profiler.trace`` when a directory is given, no-op otherwise.

    The resulting trace loads in TensorBoard's profile plugin (or
    ``xprof``) and shows per-op device timelines — ingest kernels, MXU
    Gramian updates, collectives, and the eigensolve."""
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield


__all__ = ["StageTimes", "device_trace"]

"""Shared bounded-backoff arithmetic for transient-failure retries.

One spelling of the retry delay policy, used by both network clients —
``sources/rest.py`` (the genomics REST backend) and ``serve/client.py``
(the resident-service HTTP client) — so their backoff behavior cannot
drift. Two rules:

- **full jitter**: delay uniform in ``[0, min(cap, base·2^attempt)]`` —
  the AWS-architecture-blog shape that decorrelates a thundering herd of
  retrying clients while keeping the expected delay half the ceiling;
- **Retry-After**: when the server SAYS when to come back (429/503), the
  client honors it — capped by the same ``cap`` so a hostile or broken
  header can never park a pipeline for an hour.
"""

from __future__ import annotations

import random
from email.utils import parsedate_to_datetime
from typing import Mapping, Optional


def full_jitter_delay(
    attempt: int,
    base: float,
    cap: float,
    rng: Optional[random.Random] = None,
) -> float:
    """Exponential backoff with full jitter: uniform in
    ``[0, min(cap, base * 2**attempt)]``. ``attempt`` is 0-based."""
    ceiling = min(float(cap), float(base) * (2 ** int(attempt)))
    if rng is None:
        rng = random.Random()
    return rng.uniform(0.0, ceiling)


def retry_after_seconds(
    headers: Optional[Mapping], cap: float
) -> Optional[float]:
    """Parse a ``Retry-After`` header (delta-seconds or HTTP-date) into a
    delay in seconds, clamped to ``[0, cap]``; ``None`` when the header is
    absent or unparseable (the caller falls back to jittered backoff)."""
    if headers is None:
        return None
    value = headers.get("Retry-After")
    if value is None:
        return None
    value = str(value).strip()
    try:
        seconds = float(value)
    except ValueError:
        try:
            target = parsedate_to_datetime(value)
        except (TypeError, ValueError):
            return None
        if target is None:
            return None
        import datetime

        now = datetime.datetime.now(
            target.tzinfo if target.tzinfo is not None else None
        )
        seconds = (target - now).total_seconds()
    return max(0.0, min(float(cap), seconds))


__all__ = ["full_jitter_delay", "retry_after_seconds"]

"""Deterministic fault-injection harness.

A production pipeline's recovery story is only as good as the crashes it
has actually survived. This module turns "what if the process dies right
here?" into a reproducible test input: a **fault plan** — a short spec
string, activated by the ``SPARK_EXAMPLES_TPU_FAULTS`` environment
variable or ``--fault-plan`` — names exactly which registered site fires
which fault on which occurrence, and nothing else in the process changes.
With no plan configured every hook is a cheap no-op (one dict lookup on a
``None``), so the hooks stay in production code paths permanently.

Spec grammar (comma-separated entries)::

    action@site[#nth][=arg]

    kill@driver.post-flush            # SIGKILL self at the 1st hit
    kill@checkpoint.mid-write#2       # ... at the 2nd hit of that site
    raise@driver.pre-finalize         # raise InjectedFault (an Exception)
    crash@serve.worker.mid-job        # raise InjectedWorkerCrash (a
                                      #   BaseException: escapes `except
                                      #   Exception` — a dead thread)
    ioerror@files.read#3              # raise OSError at an IO boundary
    truncate@files.read=4096          # truncate that read to 4096 bytes
    slow@rest.post=0.05               # sleep 0.05s at that boundary

Each entry fires exactly once, at the ``nth`` (default 1st) hit of its
site — the plan is a deterministic schedule, not a probability. Sites are
**registered**: :data:`KILL_POINTS` and :data:`IO_POINTS` are the closed
catalogues (a typo'd site name in a plan raises at configure time, and a
typo'd site name in code raises at the hook call), so the chaos test
matrix in ``tests/test_faults.py`` can enumerate every kill-point and
know the list is complete.

Two hook shapes:

- :func:`kill_point(site)` — control-flow points (the driver's
  checkpoint/finalize seams, the serve worker's claim/mid-job seams).
  Supports ``kill`` / ``raise`` / ``crash``.
- :func:`io_point(site, data=None)` — data-plane boundaries (source
  reads, REST posts). Supports ``ioerror`` / ``truncate`` / ``slow``
  (plus ``kill``), and returns the possibly-truncated payload.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

#: Registered control-flow kill-points (site → where it lives). The chaos
#: matrix (tests/test_faults.py, ci.sh faults stage) iterates the driver.*
#: and checkpoint.* entries and asserts kill + resume parity at each.
KILL_POINTS: Dict[str, str] = {
    "driver.post-flush": (
        "pipeline/checkpoint.py:GramianFeeder.save — after the accumulator "
        "flushed and synced, before the checkpoint artifact write begins"
    ),
    "checkpoint.mid-write": (
        "pipeline/checkpoint.py:save_gramian_checkpoint — after the temp "
        "file is fully written, before the atomic os.replace publish"
    ),
    "checkpoint.post-save": (
        "pipeline/checkpoint.py:save_gramian_checkpoint — after the atomic "
        "publish, before the feeder records the new cursor"
    ),
    "driver.pre-finalize": (
        "pipeline/pca_driver.py — every ingested row accumulated (final "
        "checkpoint written when enabled), before the finalize reduce"
    ),
    "serve.worker.claim": (
        "serve/daemon.py:_run_job — job claimed and flipped to running, "
        "BEFORE any device work (the requeue-eligible window)"
    ),
    "serve.worker.mid-job": (
        "serve/daemon.py:_run_job — device work marked begun, executor "
        "about to run (a crash here must NOT be requeued)"
    ),
    "serve.lease.pre-renew": (
        "serve/daemon.py:_lease_tick — this replica owns >= 1 job lease "
        "and is about to renew them (a kill here is the canonical host "
        "loss: every lease expires unrenewed and a peer replica steals "
        "the jobs)"
    ),
    "serve.steal.pre-claim": (
        "serve/daemon.py:_steal_expired — an expired foreign lease was "
        "identified and the fencing epoch is about to be link-claimed "
        "(a kill here must leave the job claimable by any other replica "
        "— no half-taken lease)"
    ),
    "serve.submit.post-accept": (
        "serve/daemon.py:submit — the accepted record is durably "
        "journaled, the lease NOT yet claimed (a kill here strands an "
        "accepted-but-never-leased job: the orphan-adoption branch of "
        "the steal scan must reclaim it via the dead owner's stale "
        "heartbeat)"
    ),
    "serve.lease.post-claim": (
        "serve/daemon.py:submit/_replay_journal/_steal_one — a lease "
        "epoch was link-claimed on disk, its journal `lease` record NOT "
        "yet appended (a kill here leaves an unjournaled lease file: "
        "the fold's fence stays below the claimed epoch until a later "
        "claimant re-journals above it, and the expired file itself "
        "makes the job stealable)"
    ),
    "analysis.pre-manifest": (
        "analyses/base.py:finish_analysis_run — every site streamed and "
        "every per-site output published, before the warm-ledger record "
        "and the manifest write (a kill here must leave the atomic "
        "outputs complete and the manifest absent, never half-written)"
    ),
}

#: Registered IO-boundary fault sites.
IO_POINTS: Dict[str, str] = {
    "files.read": (
        "sources/stream.py:iter_byte_windows — one streamed read window, "
        "EVERY file ingest path (wire tables, packed staging, streaming; "
        "truncate simulates a truncated file; ioerror a failing disk)"
    ),
    "rest.post": (
        "sources/rest.py:RestClient._post — one transport attempt "
        "(ioerror exercises the retry/backoff loop)"
    ),
}

#: IO points whose hook carries a byte payload ``truncate`` can shorten.
#: ``rest.post`` passes no data — a truncate there would be a silent no-op
#: that still counts as fired, so the grammar rejects it.
TRUNCATE_IO_POINTS = ("files.read",)

_ACTIONS = ("kill", "raise", "crash", "ioerror", "truncate", "slow")
_KILL_ACTIONS = ("kill", "raise", "crash")
_IO_ACTIONS = ("kill", "ioerror", "truncate", "slow")

ENV_VAR = "SPARK_EXAMPLES_TPU_FAULTS"


class FaultSpecError(ValueError):
    """A malformed fault-plan spec (bad grammar, unknown site/action)."""


class InjectedFault(RuntimeError):
    """The ``raise`` action: an ordinary exception a driver run surfaces
    as a failed run (normal error handling applies)."""


class InjectedWorkerCrash(BaseException):
    """The ``crash`` action: deliberately NOT an :class:`Exception`, so it
    escapes ``except Exception`` job-failure handling and kills the thread
    it fires on — the reproducible stand-in for a worker thread dying."""


@dataclass
class _Entry:
    action: str
    site: str
    nth: int
    arg: Optional[str]
    fired: bool = False


def parse_plan(spec: str) -> List[_Entry]:
    """Parse one plan spec; raises :class:`FaultSpecError` on bad grammar,
    unknown sites, unknown actions, or an action/site shape mismatch."""
    entries: List[_Entry] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        body, arg = (raw.split("=", 1) + [None])[:2] if "=" in raw else (raw, None)
        head, nth_text = (
            body.split("#", 1) if "#" in body else (body, "1")
        )
        if "@" not in head:
            raise FaultSpecError(
                f"fault entry {raw!r} is not action@site[#nth][=arg]"
            )
        action, site = head.split("@", 1)
        if action not in _ACTIONS:
            raise FaultSpecError(
                f"unknown fault action {action!r} (one of {_ACTIONS})"
            )
        if site in KILL_POINTS:
            if action not in _KILL_ACTIONS:
                raise FaultSpecError(
                    f"action {action!r} is not valid at kill-point {site!r} "
                    f"(one of {_KILL_ACTIONS})"
                )
        elif site in IO_POINTS:
            if action not in _IO_ACTIONS:
                raise FaultSpecError(
                    f"action {action!r} is not valid at IO point {site!r} "
                    f"(one of {_IO_ACTIONS})"
                )
        else:
            raise FaultSpecError(
                f"unknown fault site {site!r}; registered sites: "
                f"{sorted(KILL_POINTS) + sorted(IO_POINTS)}"
            )
        try:
            nth = int(nth_text)
        except ValueError:
            raise FaultSpecError(f"bad occurrence count in {raw!r}") from None
        if nth < 1:
            raise FaultSpecError(f"occurrence count must be >= 1 in {raw!r}")
        if action == "truncate":
            if arg is None or not arg.isdigit():
                raise FaultSpecError(
                    f"truncate needs =BYTES, got {raw!r}"
                )
            if site not in TRUNCATE_IO_POINTS:
                raise FaultSpecError(
                    f"truncate has no payload to shorten at {site!r} "
                    f"(valid at {TRUNCATE_IO_POINTS})"
                )
        if action == "slow":
            try:
                float(arg if arg is not None else "")
            except ValueError:
                raise FaultSpecError(
                    f"slow needs =SECONDS, got {raw!r}"
                ) from None
        entries.append(_Entry(action=action, site=site, nth=nth, arg=arg))
    return entries


# lock order: fault-plan lock is a leaf — nothing else is acquired while
# holding it (hit counting and entry matching only; actions fire OUTSIDE).
_lock = threading.Lock()
_UNSET = object()
_plan_entries: object = _UNSET  # _UNSET | None | List[_Entry]
_hits: Dict[str, int] = {}
_injected = 0

#: Pre-fire flush hooks (``obs/recorder.py``'s crash durability): every
#: registered hook runs IMMEDIATELY BEFORE a matched fault fires — for the
#: ``kill`` action that is the last Python the process executes, so the
#: flight recorder's ring reaches disk before the SIGKILL the chaos
#: harness is about to assert recovery from. Hooks must be cheap, must
#: not raise (exceptions are swallowed: a telemetry bug must not turn a
#: deterministic kill-point into a different crash), and run on the
#: faulting thread.
_flush_hooks: List[Callable[[], None]] = []


def add_flush_hook(fn: Callable[[], None]) -> None:
    """Register a pre-fire flush hook (idempotent per callable)."""
    with _lock:
        if fn not in _flush_hooks:
            _flush_hooks.append(fn)


def remove_flush_hook(fn: Callable[[], None]) -> None:
    with _lock:
        if fn in _flush_hooks:
            _flush_hooks.remove(fn)


def _run_flush_hooks() -> None:
    with _lock:
        hooks = list(_flush_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:
            pass


def configure(spec: Optional[str]) -> None:
    """(Re)configure the process-wide fault plan. ``None``/empty disables.
    Resets per-site hit counts and the injected-fault counter — each
    configure starts a fresh deterministic schedule."""
    global _plan_entries, _injected
    entries = parse_plan(spec) if spec else None
    with _lock:
        _plan_entries = entries
        _hits.clear()
        _injected = 0


def _entries() -> Optional[List[_Entry]]:
    """The active plan, lazily parsed from the environment on first use."""
    global _plan_entries
    with _lock:
        if _plan_entries is _UNSET:
            spec = os.environ.get(ENV_VAR)
            _plan_entries = parse_plan(spec) if spec else None
        return _plan_entries  # type: ignore[return-value]


def active() -> bool:
    """Whether a non-empty fault plan is configured."""
    entries = _entries()
    return bool(entries)


def injected_count() -> int:
    """How many faults actually fired in this process so far — recorded in
    the run manifest's ``resume.faults_injected`` field."""
    with _lock:
        return _injected


def _match(site: str) -> Optional[_Entry]:
    """Count one hit of ``site``; return the entry that fires now, if any.
    Pure bookkeeping under the leaf lock — the action runs at the caller."""
    global _injected
    entries = _entries()
    if not entries:
        return None
    with _lock:
        count = _hits.get(site, 0) + 1
        _hits[site] = count
        for entry in entries:
            if entry.site == site and not entry.fired and entry.nth == count:
                entry.fired = True
                _injected += 1
                return entry
    return None


def _fire_control(entry: _Entry) -> None:
    if entry.action == "kill":
        # A real crash: no atexit, no finally blocks, no flushes — the
        # exact shape of an OOM-kill or a preemption. The chaos matrix
        # asserts recovery from THIS, not from polite exceptions.
        os.kill(os.getpid(), signal.SIGKILL)
    if entry.action == "crash":
        raise InjectedWorkerCrash(f"injected worker crash at {entry.site}")
    raise InjectedFault(f"injected fault at {entry.site}")


def kill_point(site: str) -> None:
    """One registered control-flow kill-point. No-op without a matching
    plan entry; fires ``kill``/``raise``/``crash`` when one matches."""
    if site not in KILL_POINTS:
        raise KeyError(f"unregistered kill-point {site!r}")
    entry = _match(site)
    if entry is not None:
        _run_flush_hooks()
        _fire_control(entry)


def io_point(site: str, data: Optional[bytes] = None) -> Optional[bytes]:
    """One registered IO-boundary site; returns ``data`` (possibly
    truncated). ``ioerror`` raises :class:`OSError`, ``slow`` sleeps,
    ``truncate`` shortens the payload, ``kill`` SIGKILLs."""
    if site not in IO_POINTS:
        raise KeyError(f"unregistered IO point {site!r}")
    entry = _match(site)
    if entry is None:
        return data
    if entry.action == "kill":
        _run_flush_hooks()
        os.kill(os.getpid(), signal.SIGKILL)
    if entry.action == "ioerror":
        raise OSError(f"injected IO error at {site}")
    if entry.action == "slow":
        time.sleep(float(entry.arg or 0))
        return data
    # truncate
    limit = int(entry.arg or 0)
    return data[:limit] if data is not None else data


def snapshot() -> Tuple[int, Dict[str, int]]:
    """(injected_count, per-site hit counts) — test introspection."""
    with _lock:
        return _injected, dict(_hits)


def registered_kill_points() -> Dict[str, str]:
    """The closed kill-point catalogue, ``{site: where it lives}`` — a
    defensive copy. ``graftcheck proto``'s GP006 rule compares every
    model-reachable crash transition against THIS set: a protocol state
    the model can crash in that no registered site covers is a chaos-
    matrix blind spot, reported as a finding."""
    return dict(KILL_POINTS)


def registered_io_points() -> Dict[str, str]:
    """The closed IO-point catalogue, ``{site: where it lives}`` — a
    defensive copy (same introspection contract as
    :func:`registered_kill_points`)."""
    return dict(IO_POINTS)


__all__ = [
    "ENV_VAR",
    "KILL_POINTS",
    "IO_POINTS",
    "TRUNCATE_IO_POINTS",
    "FaultSpecError",
    "InjectedFault",
    "InjectedWorkerCrash",
    "parse_plan",
    "configure",
    "active",
    "injected_count",
    "add_flush_hook",
    "remove_flush_hook",
    "kill_point",
    "io_point",
    "registered_io_points",
    "registered_kill_points",
    "snapshot",
]

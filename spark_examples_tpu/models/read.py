"""Read data model and builder.

Mirrors the serializable ``Read`` case class and ``ReadBuilder`` at
``rdd/ReadsRDD.scala:38-87``: alignment fields are flattened (position,
reference name, mapping quality pulled out of the nested alignment message)
and the structured CIGAR is re-encoded as a SAM-style string via the
operation→letter map at ``rdd/ReadsRDD.scala:46-55``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence, Tuple


@dataclass(frozen=True)
class ReadKey:
    """Indexes a mapped read to its partition (``rdd/ReadsRDD.scala:133-134``)."""

    sequence: str
    position: int


@dataclass(frozen=True)
class Read:
    """A serializable aligned read (``rdd/ReadsRDD.scala:38-42``)."""

    aligned_quality: Tuple[int, ...]
    cigar: str
    id: str
    mapping_quality: int
    mate_position: Optional[int]
    mate_reference_name: Optional[str]
    fragment_name: str
    aligned_sequence: str
    position: int
    read_group_set_id: str
    reference_name: str
    info: Mapping[str, Sequence[str]] = field(default_factory=dict)
    fragment_length: int = 0


class ReadBuilder:
    """Wire-format dict → ``Read`` (``rdd/ReadsRDD.scala:44-87``)."""

    CIGAR_MATCH = {
        "ALIGNMENT_MATCH": "M",
        "CLIP_HARD": "H",
        "CLIP_SOFT": "S",
        "DELETE": "D",
        "INSERT": "I",
        "PAD": "P",
        "SEQUENCE_MATCH": "=",
        "SEQUENCE_MISMATCH": "X",
        "SKIP": "N",
    }

    @classmethod
    def build(cls, r: Mapping) -> Tuple[ReadKey, Read]:
        alignment = r["alignment"]
        position = alignment["position"]
        read_key = ReadKey(position["referenceName"], int(position["position"]))

        cigar = "".join(
            f"{int(unit['operationLength'])}{cls.CIGAR_MATCH[unit['operation']]}"
            for unit in alignment.get("cigar", [])
        )

        mate = r.get("nextMatePosition")
        read = Read(
            aligned_quality=tuple(int(q) for q in r.get("alignedQuality", [])),
            cigar=cigar,
            id=r.get("id"),
            mapping_quality=int(alignment.get("mappingQuality", 0)),
            mate_position=int(mate["position"]) if mate else None,
            mate_reference_name=mate["referenceName"] if mate else None,
            fragment_name=r.get("fragmentName"),
            aligned_sequence=r.get("alignedSequence", ""),
            position=int(position["position"]),
            read_group_set_id=r.get("readGroupSetId"),
            reference_name=position["referenceName"],
            info=r.get("info", {}),
            fragment_length=int(r.get("fragmentLength", 0)),
        )
        return (read_key, read)


__all__ = ["Read", "ReadKey", "ReadBuilder"]

from spark_examples_tpu.models.variant import Call, Variant, VariantKey, VariantsBuilder
from spark_examples_tpu.models.read import Read, ReadKey, ReadBuilder

__all__ = [
    "Call",
    "Variant",
    "VariantKey",
    "VariantsBuilder",
    "Read",
    "ReadKey",
    "ReadBuilder",
]

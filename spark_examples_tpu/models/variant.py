"""Variant / Call data model and builder.

Parity notes (reference files cited per field):

- ``Call`` and ``Variant`` mirror the serializable case classes at
  ``rdd/VariantsRDD.scala:43-51``. The reference needed its own copies because
  the Java API model is not serializable; we need plain records because the
  wire format (JSON dicts) must be converted once into cheap, immutable,
  hashable objects before they fan out into host pipelines and device batches.
- ``VariantsBuilder.normalize`` reproduces the regex semantics of
  ``rdd/VariantsRDD.scala:89-96``: reference names are matched against
  ``([a-z]*)?([0-9]*)`` as a FULL match, the numeric group is kept (so
  ``chr17`` → ``17``), and any non-matching contig (``X``, ``Y``,
  ``GL000229.1``, …) is DROPPED by returning ``None``.
- ``VariantsBuilder.build`` reproduces ``rdd/VariantsRDD.scala:98-149``: the
  partition key is ``VariantKey(raw_reference_name, start)`` (the *raw* name,
  not the normalized one), while ``Variant.contig`` holds the normalized name.
- ``Variant.variant_key()`` reproduces the murmur3_128 matching key of
  ``VariantsPca.scala:71-86`` (contig, start, end, referenceBases, joined
  alternateBases — UTF-8 strings and little-endian longs, hex digest).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from spark_examples_tpu.utils.murmur3 import murmur3_x64_128_hex


@dataclass(frozen=True)
class VariantKey:
    """Indexes a variant to its partition (``rdd/VariantsRDD.scala:246``)."""

    contig: str
    position: int


@dataclass(frozen=True)
class Call:
    """One sample's call on a variant (``rdd/VariantsRDD.scala:43-45``)."""

    callset_id: str
    callset_name: str
    genotype: Tuple[int, ...]
    genotype_likelihood: Optional[Tuple[float, ...]] = None
    phaseset: Optional[str] = None
    info: Mapping[str, Sequence[str]] = field(default_factory=dict)

    def has_variation(self) -> bool:
        """True iff any genotype allele is non-reference.

        Mirrors ``call.genotype.foldLeft(false)(_ || _ > 0)``
        (``VariantsPca.scala:67``).
        """
        return any(g > 0 for g in self.genotype)


@dataclass(frozen=True)
class Variant:
    """A serializable variant record (``rdd/VariantsRDD.scala:48-51``)."""

    contig: str
    id: str
    names: Optional[Tuple[str, ...]]
    start: int
    end: int
    reference_bases: str
    alternate_bases: Optional[Tuple[str, ...]]
    info: Mapping[str, Sequence[str]]
    created: int
    variant_set_id: str
    calls: Optional[Tuple[Call, ...]]

    def variant_key(self, debug: bool = False) -> str:
        """Cross-dataset matching key (``VariantsPca.scala:71-86``)."""
        alternate = "".join(self.alternate_bases) if self.alternate_bases else ""
        if debug:
            print(
                f"{self.contig}: ({self.start}, {self.end}) "
                f"ref={self.reference_bases} alt={alternate}"
            )
        payload = (
            self.contig.encode("utf-8")
            + int(self.start).to_bytes(8, "little", signed=True)
            + int(self.end).to_bytes(8, "little", signed=True)
            + self.reference_bases.encode("utf-8")
            + alternate.encode("utf-8")
        )
        return murmur3_x64_128_hex(payload)

    def to_json(self) -> Dict:
        """Back-conversion to the wire format.

        The analog of ``Variant.toJavaVariant`` (``rdd/VariantsRDD.scala:53-83``),
        used by the round-trip smoke check in the Klotho example
        (``SearchVariantsExample.scala:77-79``) and by the checkpoint writer.
        """
        out: Dict = {
            "referenceName": self.contig,
            "created": self.created,
            "variantSetId": self.variant_set_id,
            "id": self.id,
            "info": {k: list(v) for k, v in self.info.items()},
            "start": self.start,
            "end": self.end,
            "referenceBases": self.reference_bases,
        }
        if self.alternate_bases is not None:
            out["alternateBases"] = list(self.alternate_bases)
        if self.names is not None:
            out["names"] = list(self.names)
        if self.calls is not None:
            calls = []
            for c in self.calls:
                call: Dict = {
                    "callSetId": c.callset_id,
                    "callSetName": c.callset_name,
                    "genotype": list(c.genotype),
                    "info": {k: list(v) for k, v in c.info.items()},
                    "phaseset": c.phaseset,
                }
                if c.genotype_likelihood is not None:
                    call["genotypeLikelihood"] = list(c.genotype_likelihood)
                calls.append(call)
            out["calls"] = calls
        return out


class VariantsBuilder:
    """Wire-format dict → ``Variant`` (``rdd/VariantsRDD.scala:87-149``)."""

    _REF_NAME_RE = re.compile(r"([a-z]*)?([0-9]*)")

    @classmethod
    def normalize(cls, reference_name: str) -> Optional[str]:
        """Strip a lowercase prefix, keep digits; drop anything else.

        Full-match semantics of the Scala pattern match on
        ``([a-z]*)?([0-9]*)`` (``rdd/VariantsRDD.scala:89-96``): ``chr17`` →
        ``17``, ``17`` → ``17``, but ``X``/``MT``/``GL000229.1`` → ``None``.
        """
        m = cls._REF_NAME_RE.fullmatch(reference_name)
        if m is None:
            return None
        return m.group(2)

    @classmethod
    def build(cls, r: Mapping) -> Optional[Tuple[VariantKey, Variant]]:
        """Build one variant, or ``None`` for non-normalizable contigs."""
        variant_key = VariantKey(r["referenceName"], int(r["start"]))

        calls: Optional[Tuple[Call, ...]]
        if "calls" in r:
            calls = tuple(
                Call(
                    callset_id=c.get("callSetId"),
                    callset_name=c.get("callSetName"),
                    genotype=tuple(int(g) for g in c.get("genotype", [])),
                    genotype_likelihood=(
                        tuple(float(x) for x in c["genotypeLikelihood"])
                        if "genotypeLikelihood" in c
                        else None
                    ),
                    phaseset=c.get("phaseset"),
                    info=c.get("info", {}),
                )
                for c in r["calls"]
            )
        else:
            calls = None

        reference_name = cls.normalize(r["referenceName"])
        if reference_name is None:
            return None

        variant = Variant(
            contig=reference_name,
            id=r.get("id"),
            names=tuple(r["names"]) if "names" in r else None,
            start=int(r["start"]),
            end=int(r["end"]),
            reference_bases=r.get("referenceBases"),
            alternate_bases=(
                tuple(r["alternateBases"]) if "alternateBases" in r else None
            ),
            info=r.get("info", {}),
            created=int(r.get("created", 0)),
            variant_set_id=r.get("variantSetId"),
            calls=calls,
        )
        return (variant_key, variant)


__all__ = ["Call", "Variant", "VariantKey", "VariantsBuilder"]

"""Public-data constants.

Mirrors ``GoogleGenomicsPublicData`` (``SearchVariantsExample.scala:27-31``)
and ``Examples`` (``SearchReadsExample.scala:30-67``).
"""

from typing import Dict


class GoogleGenomicsPublicData:
    PLATINUM_GENOMES = "3049512673186936334"
    THOUSAND_GENOMES_PHASE_1 = "10473108253681171589"
    THOUSAND_GENOMES_PHASE_3 = "4252737135923902652"


class Examples:
    GOOGLE_1KG_HG00096_READSET = "CMvnhpKTFhCwvIWYw9eikzQ"
    GOOGLE_EXAMPLE_READSET = "CMvnhpKTFhD04eLE-q2yxnU"
    GOOGLE_DREAM_SET3_NORMAL = "CPHG3MzoCRDRkqXzk7b6l_kB"
    GOOGLE_DREAM_SET3_TUMOR = "CPHG3MzoCRCO1rDx8pOY6yo"

    #: SNP @ 6889648 — cilantro/soap variant near OR10A2
    CILANTRO = 6889648

    HUMAN_CHROMOSOMES: Dict[str, int] = {
        "1": 249250621,
        "2": 243199373,
        "3": 198022430,
        "4": 191154276,
        "5": 180915260,
        "6": 171115067,
        "7": 159138663,
        "8": 146364022,
        "9": 141213431,
        "10": 135534747,
        "11": 135006516,
        "12": 133851895,
        "13": 115169878,
        "14": 107349540,
        "15": 102531392,
        "16": 90354753,
        "17": 81195210,
        "18": 78077248,
        "19": 59128983,
        "20": 63025520,
        "21": 48129895,
        "22": 51304566,
        "X": 155270560,
        "Y": 59373566,
    }


__all__ = ["GoogleGenomicsPublicData", "Examples"]

"""Crash-durable flight recorder: the last seconds before any ``kill -9``.

Since PR 13 a job's life can span N replica daemons — accepted on one,
stolen and finished by another — and the chaos harness SIGKILLs real
processes at every registered kill-point. The in-memory telemetry
(``obs/spans.py``, the metrics registry) dies with the process, so a
post-mortem has only the journal's admission facts, none of the
*timeline*. The :class:`FlightRecorder` closes that gap:

- **a bounded per-replica event ring**: :meth:`record` appends one event
  dict to an in-memory deque in O(1) under a leaf lock. The ring holds
  UNFLUSHED events only and is bounded (``capacity``); past the bound the
  oldest pending event is dropped and counted — the recorder can never
  become the unbounded buffer ``graftcheck hostmem`` forbids everywhere
  else;
- **crash-durable flushes**: :meth:`flush` drains the ring to an
  append-only JSONL segment file under ``<run_dir>/trace/``. The serve
  daemon flushes at every job terminal transition, at drain, and — the
  load-bearing one — at every registered fault kill-point *before* the
  fault fires (``utils/faults.py:add_flush_hook``), so the chaos
  harness's ``kill -9`` always lands on a segment that already contains
  the events leading up to it. An ``atexit`` hook catches polite exits;
  segments merge via the ``trace export`` CLI verb
  (``python -m spark_examples_tpu trace export``, ``obs/trace.py``);
- **torn-tail tolerance**: a kill mid-append can tear at most the last
  line of a segment; readers (``obs/trace.py``) skip unparseable lines,
  exactly like the journal fold.

Event schema (one JSON object per line)::

    {"ts": 1722…,               # unix seconds (float)
     "name": "job",             # what happened
     "ph": "B" | "E" | "i",     # span begin / span end / instant
     "trace": "…32 hex…",       # trace id (one job = one trace)
     "job": "job-a-000001",
     "replica": "a",            # or "solo"
     "pid": 1234,
     "tid": "small-0",          # executor slice, or "control"
     "args": {…}}               # free-form attributes (epoch, status, …)

``B``/``E`` pairs are matched by ``(replica, job, name)`` at export time
(``obs/trace.py``); a ``B`` whose ``E`` died with its process is closed
as a truncated span by the exporter, never left orphaned.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

#: Segment files live here under the shared run directory — one file per
#: replica incarnation, append-only, merged by the ``trace export`` verb.
TRACE_DIRNAME = "trace"

#: Default ring bound: unflushed events held in memory. Control-plane
#: event rates are a handful per job, so thousands of pending events mean
#: flushing stopped — drop the oldest and say so, never grow.
DEFAULT_CAPACITY = 4096


def trace_dir(run_dir: str) -> str:
    return os.path.join(run_dir, TRACE_DIRNAME)


class FlightRecorder:
    """One process's half of the run directory's flight-recorder record;
    see the module docstring for the durability contract."""

    def __init__(
        self,
        run_dir: str,
        name: str,
        capacity: int = DEFAULT_CAPACITY,
        clock: Callable[[], float] = time.time,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in str(name)
        )
        if not safe:
            raise ValueError(f"recorder name {name!r} is empty once sanitized")
        self.run_dir = run_dir
        self.name = safe
        #: Segment name carries the pid so a restarted replica with the
        #: same id appends to its OWN segment — two incarnations' torn
        #: tails must never interleave in one file.
        self.path = os.path.join(
            trace_dir(run_dir), f"{safe}.{os.getpid()}.jsonl"
        )
        self.capacity = int(capacity)
        self._clock = clock
        # lock order: recorder lock is a leaf — nothing else is acquired
        # while holding it (append/drain bookkeeping only; file writes
        # happen holding it too but acquire no further locks).
        self._lock = threading.Lock()
        self._pending: Deque[Dict] = deque()
        self._file = None
        self._closed = False
        self.dropped = 0
        self.recorded = 0
        self.flushed = 0
        atexit.register(self._atexit)

    # -------------------------------------------------------------- record

    def record(
        self,
        name: str,
        ph: str = "i",
        trace: Optional[str] = None,
        job: Optional[str] = None,
        tid: str = "control",
        **args,
    ) -> None:
        """Append one event to the ring — O(1), never touches the disk.
        ``ph`` is the Chrome-trace phase this event exports as: ``B``/``E``
        span boundaries (paired by ``(replica, job, name)``) or ``i``
        instants."""
        if ph not in ("B", "E", "i"):
            raise ValueError(f"unknown event phase {ph!r} (B, E, or i)")
        event: Dict = {
            "ts": self._clock(),
            "name": str(name),
            "ph": ph,
            "replica": self.name,
            "pid": os.getpid(),
            "tid": str(tid),
        }
        if trace is not None:
            event["trace"] = str(trace)
        if job is not None:
            event["job"] = str(job)
        if args:
            event["args"] = args
        with self._lock:
            if self._closed:
                return
            if len(self._pending) >= self.capacity:
                self._pending.popleft()
                self.dropped += 1
            self._pending.append(event)
            self.recorded += 1

    def begin(self, name: str, **kw) -> None:
        self.record(name, ph="B", **kw)

    def end(self, name: str, **kw) -> None:
        self.record(name, ph="E", **kw)

    # --------------------------------------------------------------- flush

    def flush(self, fsync: bool = True) -> int:
        """Drain every pending event to the append-only segment file;
        returns how many events landed. Safe to call from any thread and
        from the fault hook's pre-kill window — failures are swallowed
        (telemetry must never take down the run OR turn a deterministic
        kill-point into a different crash)."""
        with self._lock:
            if not self._pending:
                return 0
            events = list(self._pending)
            self._pending.clear()
            dropped, self.dropped = self.dropped, 0
            lines = events
            if dropped:
                # The gap is part of the record: a reader must know the
                # ring overflowed rather than infer silence.
                lines = [
                    {
                        "ts": events[0]["ts"],
                        "name": "ring-overflow",
                        "ph": "i",
                        "replica": self.name,
                        "pid": os.getpid(),
                        "tid": "control",
                        "args": {"dropped": dropped},
                    }
                ] + events
            try:
                if self._file is None:
                    os.makedirs(os.path.dirname(self.path), exist_ok=True)
                    self._file = open(self.path, "a", encoding="utf-8")
                for event in lines:
                    self._file.write(json.dumps(event, sort_keys=True) + "\n")
                self._file.flush()
                if fsync:
                    os.fsync(self._file.fileno())
            except Exception:
                # A failed flush (ENOSPC, unopenable dir) must not also
                # discard the timeline: restore the drained events and
                # the drop count so the next attempt retries them. A
                # half-written batch may duplicate lines on retry — the
                # exporter tolerates that; losing the pre-crash record
                # it exists to preserve would be worse.
                self._pending.extendleft(reversed(events))
                self.dropped += dropped
                return 0
            self.flushed += len(lines)
            return len(lines)

    def close(self) -> None:
        """Final flush + file close; further records are ignored (a late
        telemetry write after teardown must not resurrect the file)."""
        self.flush()
        with self._lock:
            self._closed = True
            if self._file is not None:
                try:
                    self._file.close()
                except Exception:
                    pass
                self._file = None
        # Release the atexit pin: a long-lived embedder that starts and
        # stops many services must not accumulate dead recorders.
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass

    def _atexit(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def read_segments(run_dir: str) -> List[Dict]:
    """Every event from every segment under ``<run_dir>/trace/``, in
    per-file order then globally sorted by timestamp. Torn or corrupt
    lines (a ``kill -9`` mid-append) are skipped, like the journal fold;
    non-segment files are ignored."""
    directory = trace_dir(run_dir)
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return []
    events: List[Dict] = []
    for fname in names:
        if not fname.endswith(".jsonl"):
            continue
        try:
            f = open(os.path.join(directory, fname), "r", encoding="utf-8")
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed writer
                if (
                    isinstance(event, dict)
                    and isinstance(event.get("ts"), (int, float))
                    and isinstance(event.get("name"), str)
                    and event.get("ph") in ("B", "E", "i")
                    # The merge hard-indexes the replica; a foreign JSONL
                    # dropped into trace/ must be skipped like a torn
                    # tail, never crash the export.
                    and isinstance(event.get("replica"), str)
                ):
                    events.append(event)
    events.sort(key=lambda e: e["ts"])
    return events


__all__ = [
    "DEFAULT_CAPACITY",
    "TRACE_DIRNAME",
    "FlightRecorder",
    "read_segments",
    "trace_dir",
]

"""End-to-end distributed tracing: trace ids + the merged Chrome trace.

The reference delegated its timeline to the Spark web UI's stage view
(SURVEY.md §5); our per-process spans (``obs/spans.py``) die with the
process, and since PR 13 one job's life can cross N replica daemons.
This module is the fleet-level successor:

- **trace context**: a :func:`mint_trace_id` hex id is minted where a
  job enters the system (``serve/client.py`` submit — or at admission
  for clients that send none), carried as the ``X-Trace-Id`` HTTP header
  (``serve/http.py``), stamped on the job and its journal ``accepted``
  record (``serve/journal.py``), and therefore onto every flight-recorder
  event and across every replica steal: one job = one trace id = one
  span tree, no matter which replicas touched it;
- **the merged trace** (:func:`merge_run_trace`): journals + flight-
  recorder segments (``obs/recorder.py``) from one shared run directory
  become a single Chrome-trace/Perfetto JSON — replicas as processes,
  executor slices as threads, job spans as complete ``X`` events, steals
  as ``s``/``f`` flow arrows from the dead owner's last recorded event to
  the stealer's claim. A span whose ``E`` died with its process (the
  ``kill -9`` the chaos harness loves) is closed at its replica's last
  recorded instant and marked ``truncated`` — the export never contains
  an orphan span;
- **the validator** (:func:`validate_chrome_trace`): the structural
  contract CI enforces on every exported trace — every ``B`` paired with
  a matching ``E``, every flow ``s`` paired with exactly one ``f`` (no
  orphan arrows), sane phases/timestamps throughout;
- **the CLI** (:func:`export_main`): ``python -m spark_examples_tpu
  trace export --run-dir DIR [--out FILE]`` — load the result into
  ``chrome://tracing`` or https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from spark_examples_tpu.obs.recorder import read_segments, trace_dir

#: The propagation header (``serve/client.py`` sends it, ``serve/http.py``
#: reads it). A simple hex id, not W3C traceparent: there is exactly one
#: hop and no sampling flags to carry.
TRACE_HEADER = "X-Trace-Id"

#: Accepted trace-id grammar (client-sent ids are untrusted input that
#: ends up in journal records and file contents — bounded hex only).
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,64}$")

#: Chrome-trace phases the validator accepts.
_KNOWN_PHASES = frozenset({"X", "B", "E", "i", "I", "s", "t", "f", "M"})


def mint_trace_id() -> str:
    """A fresh 128-bit lowercase-hex trace id."""
    return os.urandom(16).hex()


def normalize_trace_id(value) -> Optional[str]:
    """A validated, lowercased trace id, or ``None`` when the input is
    absent or violates the grammar (the caller then mints a fresh one —
    a malformed header must never abort an admission)."""
    if not isinstance(value, str):
        return None
    value = value.strip().lower()
    return value if _TRACE_ID_RE.match(value) else None


# ------------------------------------------------------------------ merge


def _micros(ts: float, origin: float) -> int:
    return int(round((ts - origin) * 1e6))


def _journal_facts(run_dir: str) -> Dict[str, Dict]:
    """Fold the shared journal's raw records into per-job correlation
    facts: trace id, highest lease epoch per replica, stolen flags, and
    the fenced terminal status (mirroring ``replay_journal``'s epoch
    fencing so the summary's "final state" is the one the fleet honors)."""
    from spark_examples_tpu.serve.journal import (
        iter_journal_records,
        journal_path,
    )

    jobs: Dict[str, Dict] = {}
    for record in iter_journal_records(journal_path(run_dir)):
        job_id = record.get("id")
        if not isinstance(job_id, str):
            continue
        job = jobs.setdefault(
            job_id,
            {
                "trace": None,
                "lease_epoch": 0,
                "leases": [],
                "stolen": False,
                "began": False,
                "terminals": [],
                "status": None,
            },
        )
        event = record.get("event")
        if event == "accepted":
            trace = record.get("trace")
            if isinstance(trace, str):
                job["trace"] = trace
        elif event == "began":
            job["began"] = True
        elif event == "lease":
            epoch = record.get("epoch")
            if isinstance(epoch, int):
                job["lease_epoch"] = max(job["lease_epoch"], epoch)
                job["leases"].append(
                    {
                        "epoch": epoch,
                        "replica": record.get("replica"),
                        "stolen": bool(record.get("stolen")),
                    }
                )
                if record.get("stolen"):
                    job["stolen"] = True
        elif event == "terminal":
            epoch = record.get("epoch")
            job["terminals"].append(
                (
                    epoch if isinstance(epoch, int) else None,
                    record.get("status"),
                )
            )
    for job in jobs.values():
        fence = job["lease_epoch"]
        for epoch, status in job["terminals"]:
            if epoch is None or epoch >= fence:
                job["status"] = status
        del job["terminals"]
    return jobs


def merge_run_trace(run_dir: str) -> Dict:
    """One Chrome-trace document from a run directory's flight-recorder
    segments + shared journal; see the module docstring for the mapping.
    Raises ``FileNotFoundError`` when the run dir has neither a trace
    directory nor a journal to merge."""
    events = read_segments(run_dir)
    from spark_examples_tpu.serve.journal import journal_path

    have_journal = os.path.exists(journal_path(run_dir))
    if not events and not have_journal:
        raise FileNotFoundError(
            f"nothing to merge: no segments under {trace_dir(run_dir)!r} "
            f"and no journal at {journal_path(run_dir)!r}"
        )
    facts = _journal_facts(run_dir) if have_journal else {}

    origin = min((e["ts"] for e in events), default=0.0)
    replicas = sorted({e["replica"] for e in events})
    pid_of = {replica: i + 1 for i, replica in enumerate(replicas)}
    tid_of: Dict[Tuple[str, str], int] = {}
    for replica in replicas:
        names = sorted(
            {e.get("tid", "control") for e in events if e["replica"] == replica}
        )
        for i, tid_name in enumerate(names):
            tid_of[(replica, tid_name)] = i + 1

    out: List[Dict] = []
    for replica in replicas:
        out.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid_of[replica],
                "tid": 0,
                "args": {"name": f"replica {replica}"},
            }
        )
        for (rep, tid_name), tid in tid_of.items():
            if rep == replica:
                out.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": pid_of[replica],
                        "tid": tid,
                        "args": {"name": tid_name},
                    }
                )

    # Span pairing: B/E matched per (replica, job, name) in timestamp
    # order; a B whose E died with its process closes at the replica's
    # last recorded timestamp, marked truncated — no orphan spans leave
    # this function (the acceptance contract of the chaos export).
    last_ts: Dict[str, float] = {}
    for event in events:
        last_ts[event["replica"]] = max(
            last_ts.get(event["replica"], event["ts"]), event["ts"]
        )
    open_spans: Dict[Tuple[str, str, str], List[Dict]] = {}
    #: Every event per (replica, job) in timestamp order — the steal
    #: arrows below anchor on the owner's last event AT OR BEFORE the
    #: steal, not its globally-last one (a deposed-but-alive zombie
    #: keeps recording after the steal).
    job_events: Dict[Tuple[str, str], List[Dict]] = {}

    def _common(event: Dict) -> Dict:
        entry: Dict = {
            "name": event["name"],
            "pid": pid_of[event["replica"]],
            "tid": tid_of[(event["replica"], event.get("tid", "control"))],
            "ts": _micros(event["ts"], origin),
        }
        args = dict(event.get("args") or {})
        for key in ("trace", "job"):
            if event.get(key) is not None:
                args[key] = event[key]
        args["replica"] = event["replica"]
        entry["args"] = args
        return entry

    steal_events: List[Dict] = []
    for event in events:
        key = (event["replica"], event.get("job") or "", event["name"])
        if event.get("job") is not None:
            job_events.setdefault(
                (event["replica"], event["job"]), []
            ).append(event)
        if event["ph"] == "B":
            open_spans.setdefault(key, []).append(event)
            continue
        if event["ph"] == "E":
            stack = open_spans.get(key)
            if stack:
                begin = stack.pop()
                entry = _common(begin)
                entry["ph"] = "X"
                entry["dur"] = max(
                    0, _micros(event["ts"], origin) - entry["ts"]
                )
                entry["args"].update(dict(event.get("args") or {}))
                out.append(entry)
            else:
                # An end whose begin predates the recorder (or was dropped
                # by the ring): surfaced as an instant, never invented as
                # a span.
                entry = _common(event)
                entry["ph"] = "i"
                entry["s"] = "t"
                entry["args"]["unmatched_end"] = True
                out.append(entry)
            continue
        # Instants.
        entry = _common(event)
        entry["ph"] = "i"
        entry["s"] = "t"
        out.append(entry)
        if event["name"] == "steal":
            steal_events.append(event)

    truncated = 0
    for (replica, _job, _name), stack in open_spans.items():
        for begin in stack:
            entry = _common(begin)
            entry["ph"] = "X"
            entry["dur"] = max(
                0, _micros(last_ts[replica], origin) - entry["ts"]
            )
            entry["args"]["truncated"] = True
            out.append(entry)
            truncated += 1

    # Steal edges: a flow arrow from the dead owner's last recorded event
    # for the job to the stealer's claim. The anchor is the owner's last
    # event AT OR BEFORE the steal (a deposed-but-alive zombie may keep
    # recording after it); under cross-host clock skew where EVERY owner
    # event postdates the steal, the earliest one anchors — a skewed
    # arrow beats a missing edge. A replica whose recorder never reached
    # disk contributes no arrow (the journal summary still names the
    # steal).
    arrows = 0
    for event in steal_events:
        job_id = event.get("job")
        owner = (event.get("args") or {}).get("from")
        if not job_id or not isinstance(owner, str) or owner not in pid_of:
            continue
        candidates = job_events.get((owner, job_id))
        if not candidates:
            continue
        anchor = next(
            (
                e
                for e in reversed(candidates)
                if e["ts"] <= event["ts"]
            ),
            candidates[0],
        )
        arrows += 1
        flow_name = f"steal {job_id}"
        out.append(
            {
                "ph": "s",
                "cat": "steal",
                "name": flow_name,
                "id": arrows,
                "pid": pid_of[owner],
                "tid": tid_of[(owner, anchor.get("tid", "control"))],
                "ts": _micros(anchor["ts"], origin),
            }
        )
        out.append(
            {
                "ph": "f",
                "bp": "e",
                "cat": "steal",
                "name": flow_name,
                "id": arrows,
                "pid": pid_of[event["replica"]],
                "tid": tid_of[(event["replica"], event.get("tid", "control"))],
                "ts": _micros(event["ts"], origin),
            }
        )

    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_dir": os.path.abspath(run_dir),
            "origin_unix": origin,
            "replicas": replicas,
            "recorder_events": len(events),
            "truncated_spans": truncated,
            "steal_arrows": arrows,
            "jobs": facts,
        },
    }


# --------------------------------------------------------------- validate


def validate_chrome_trace(doc) -> List[str]:
    """Structural validation of a Chrome-trace document; returns the list
    of problems (empty = well-formed). The contract CI enforces on every
    exported trace: known phases, numeric timestamps, every ``B`` closed
    by a matching ``E`` in order (durations ``X`` need no pairing), and
    every flow arrow whole — exactly one ``s`` and one ``f`` per id."""
    errors: List[str] = []
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return ["trace is not an object with a 'traceEvents' list"]
    stacks: Dict[Tuple, List[str]] = {}
    flows: Dict[object, Dict[str, int]] = {}
    for i, event in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(event, dict):
            errors.append(f"{where} is not an object")
            continue
        ph = event.get("ph")
        if ph not in _KNOWN_PHASES:
            errors.append(f"{where} has unknown phase {ph!r}")
            continue
        if not isinstance(event.get("name"), str) or not event["name"]:
            errors.append(f"{where} ({ph}) missing string 'name'")
        if ph == "M":
            continue
        if not isinstance(event.get("ts"), (int, float)):
            errors.append(f"{where} ({event.get('name')!r}) missing numeric 'ts'")
        key = (event.get("pid"), event.get("tid"))
        if ph == "X":
            dur = event.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(
                    f"{where} ({event.get('name')!r}) X event has bad "
                    f"dur {dur!r}"
                )
        elif ph == "B":
            stacks.setdefault(key, []).append(event.get("name") or "")
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                errors.append(
                    f"{where}: E {event.get('name')!r} on pid/tid {key} "
                    "with no open B (orphan end)"
                )
            else:
                opened = stack.pop()
                name = event.get("name")
                if name and name != opened:
                    errors.append(
                        f"{where}: E {name!r} closes B {opened!r} on "
                        f"pid/tid {key} (mismatched nesting)"
                    )
        elif ph in ("s", "t", "f"):
            flow_id = event.get("id")
            if flow_id is None:
                errors.append(f"{where}: flow {ph} event missing 'id'")
                continue
            counts = flows.setdefault(flow_id, {"s": 0, "t": 0, "f": 0})
            counts[ph] += 1
    for key, stack in stacks.items():
        for name in stack:
            errors.append(
                f"unclosed B {name!r} on pid/tid {key} (orphan span)"
            )
    for flow_id, counts in flows.items():
        if counts["s"] != 1 or counts["f"] != 1:
            errors.append(
                f"flow id {flow_id!r} is not a whole arrow "
                f"(s={counts['s']}, f={counts['f']}; need exactly one "
                "each — orphan flow arrow)"
            )
    return errors


# -------------------------------------------------------------------- CLI


def export_main(argv: Optional[Sequence[str]] = None) -> int:
    """The ``trace`` CLI verb: ``trace export --run-dir DIR [--out F]``.
    Exit 0 on a validated export, 1 when the merge has nothing to read or
    the result fails validation, 2 on usage errors."""
    argv = list(argv) if argv is not None else sys.argv[1:]
    if not argv or argv[0] != "export":
        print(
            "usage: python -m spark_examples_tpu trace export "
            "--run-dir DIR [--out FILE]",
            file=sys.stderr,
        )
        return 2
    parser = argparse.ArgumentParser(prog="spark_examples_tpu trace export")
    parser.add_argument(
        "--run-dir",
        required=True,
        help="The serve fleet's shared run directory (journal + trace/).",
    )
    parser.add_argument(
        "--out",
        default=None,
        metavar="FILE",
        help=(
            "Where the merged Chrome-trace JSON lands ('-' = stdout; "
            "default <run-dir>/trace/merged.trace.json). Load it in "
            "chrome://tracing or https://ui.perfetto.dev."
        ),
    )
    ns = parser.parse_args(argv[1:])
    if not os.path.isdir(ns.run_dir):
        print(f"trace export: no run dir {ns.run_dir!r}", file=sys.stderr)
        return 2
    try:
        doc = merge_run_trace(ns.run_dir)
    except FileNotFoundError as e:
        print(f"trace export: {e}", file=sys.stderr)
        return 1
    errors = validate_chrome_trace(doc)
    if errors:
        print(
            "trace export: merged trace FAILED validation:\n  "
            + "\n  ".join(errors),
            file=sys.stderr,
        )
        return 1
    summary = doc["otherData"]
    out_path = ns.out or os.path.join(
        trace_dir(ns.run_dir), "merged.trace.json"
    )
    if out_path == "-":
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
        tmp = f"{out_path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, out_path)
        print(
            f"trace export: {summary['recorder_events']} events from "
            f"{len(summary['replicas'])} replica(s), "
            f"{summary['steal_arrows']} steal arrow(s), "
            f"{summary['truncated_spans']} truncated span(s), "
            f"{len(summary['jobs'])} journaled job(s) -> {out_path}",
            file=sys.stderr,
        )
    return 0


__all__ = [
    "TRACE_HEADER",
    "export_main",
    "merge_run_trace",
    "mint_trace_id",
    "normalize_trace_id",
    "validate_chrome_trace",
]

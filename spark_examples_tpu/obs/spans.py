"""Hierarchical run spans — the structured successor of ``StageTimes``.

A :class:`SpanRecorder` holds a tree of named, timed spans. The driver
opens coarse stages (``ingest+similarity``, ``center+pca``) exactly where
``StageTimes`` used to; finer phases nest under them — the prefetch
iterator contributes its parse-time aggregate (``chunk-parse``), the
Gramian accumulators their flush aggregate (``dispatch``) and finalize
(``reduce-flush``), and the PCA stage its ``center``/``eigh`` children —
so one manifest shows where a run's wall-clock went, layer by layer.

Honest-timing semantics carried over from ``StageTimes.stage(sync=)``
(``utils/tracing.py``): dispatch is asynchronous and ``block_until_ready``
can ACK before execution completes on remote-attached backends, so a
span's wall time is only meaningful when it ends in a synchronous fetch.
``span(..., sync=fn)`` calls ``fn`` before closing the measurement and the
span records ``synced: true`` — manifest consumers can tell honest
wall-clock from dispatch-time-only numbers.

Thread model: the open-span stack is per-thread (ingest worker threads and
the driver thread each nest correctly); completed spans attach to their
parent, or to the recorder's root list when nothing is open on that
thread. Pre-measured durations recorded with :meth:`SpanRecorder.add`
(e.g. a flush-time aggregate) attach the same way.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, List, Optional


class Span:
    """One timed region: name, seconds, sync-honesty flag, children."""

    __slots__ = ("name", "seconds", "synced", "children", "started_unix")

    def __init__(self, name: str, synced: bool, started_unix: float):
        self.name = str(name)
        self.seconds: Optional[float] = None  # None while still open
        self.synced = bool(synced)
        self.children: List["Span"] = []
        self.started_unix = started_unix

    def as_dict(self) -> Dict:
        return {
            "name": self.name,
            "seconds": self.seconds,
            "synced": self.synced,
            "started_unix": self.started_unix,
            "children": [c.as_dict() for c in self.children],
        }


class SpanRecorder:
    """A tree of spans with a per-thread open stack."""

    def __init__(self) -> None:
        # lock order: recorder lock is a leaf — nothing else is acquired
        # while holding it.
        self._lock = threading.Lock()
        self.roots: List[Span] = []
        self._stacks: Dict[int, List[Span]] = {}

    def _attach(self, span: Span) -> None:
        tid = threading.get_ident()
        with self._lock:
            stack = self._stacks.get(tid)
            if stack:
                stack[-1].children.append(span)
            else:
                self.roots.append(span)

    @contextlib.contextmanager
    def span(self, name: str, sync: Optional[Callable[[], object]] = None):
        """Open a child span of the current thread's innermost open span
        (or a new root). ``sync`` is called before the measurement closes —
        pass a tiny device fetch for honest wall-clock on async backends."""
        span = Span(name, synced=sync is not None, started_unix=time.time())
        self._attach(span)
        tid = threading.get_ident()
        with self._lock:
            self._stacks.setdefault(tid, []).append(span)
        start = time.perf_counter()
        try:
            yield span
        finally:
            try:
                if sync is not None:
                    sync()
            finally:
                # The span closes and the stack pops even when the sync
                # fetch raises (device error mid-measurement) — otherwise
                # every later span on this thread would silently nest
                # under a dead parent.
                span.seconds = time.perf_counter() - start
                with self._lock:
                    stack = self._stacks.get(tid, [])
                    if span in stack:
                        # Pop through `span` (robust to a child left open
                        # by a mid-body exception: everything above it
                        # closes too).
                        del stack[stack.index(span):]
                    if not stack:
                        self._stacks.pop(tid, None)

    def add(self, name: str, seconds: float, synced: bool = False) -> None:
        """Attach a pre-measured duration (an aggregate timed elsewhere,
        e.g. total Gramian flush host time) as a closed span."""
        span = Span(name, synced=synced, started_unix=time.time())
        span.seconds = float(seconds)
        self._attach(span)

    # -------------------------------------------------------------- exports

    def as_list(self) -> List[Dict]:
        """The completed span tree, JSON-safe (open spans report
        ``seconds: null``)."""
        with self._lock:
            roots = list(self.roots)
        return [s.as_dict() for s in roots]

    def flat(self) -> List[Dict]:
        """Depth-first ``{path, seconds, synced}`` rows, '/'-joined paths —
        the grep-able form of the tree."""
        rows: List[Dict] = []

        def walk(span: Span, prefix: str) -> None:
            path = f"{prefix}/{span.name}" if prefix else span.name
            rows.append(
                {"path": path, "seconds": span.seconds, "synced": span.synced}
            )
            for child in span.children:
                walk(child, path)

        with self._lock:
            roots = list(self.roots)
        for root in roots:
            walk(root, "")
        return rows

    def find(self, path: str) -> Optional[Span]:
        """The first span at a '/'-joined path, or ``None``."""
        parts = path.split("/")
        with self._lock:
            level = list(self.roots)
        span = None
        for part in parts:
            span = next((s for s in level if s.name == part), None)
            if span is None:
                return None
            level = span.children
        return span


__all__ = ["Span", "SpanRecorder"]

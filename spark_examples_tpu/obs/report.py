"""Post-mortem fleet report: the cost observatory's offline reader.

``python -m spark_examples_tpu obs report --run-dir DIR [--json]`` folds
a serve fleet's run-directory artifacts — the shared job journal
(``serve/journal.py``), the calibration ledger (``obs/calibration.py``),
and the flight-recorder segments (``obs/recorder.py``) — into one
report: per-job predicted-vs-measured cost under the job's trace id,
per-class latency quantiles, the fleet calibration fold, and
steal/replay accounting. Every input is an append-only, torn-tail-
tolerant file, so the report works on a DEAD fleet: the chaos harness's
``kill -9``'d replicas leave exactly the artifacts this reads.

The join key is the job id; the correlation key shown to the operator is
the trace id — the same id the submit carried, the journal persisted
across steals, and the flight recorder stamped on every event, so one
report line names a job's whole fleet-side life.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from spark_examples_tpu.obs.calibration import calibration_path, fold_calibration


def _quantile(ordered: List[float], q: float) -> Optional[float]:
    """Exact linear-interpolation quantile over a SORTED sample list —
    offline reports read full ledgers, so no reservoir is needed."""
    if not ordered:
        return None
    rank = min(max(float(q), 0.0), 1.0) * (len(ordered) - 1)
    low = int(rank)
    high = min(low + 1, len(ordered) - 1)
    fraction = rank - low
    return ordered[low] * (1.0 - fraction) + ordered[high] * fraction


def _iter_ledger_records(path: str):
    """Raw calibration-ledger records, torn-tail-tolerant (the same skip
    contract as ``fold_calibration`` — an unparseable line can only be a
    crashed writer's last)."""
    try:
        f = open(path, "r", encoding="utf-8")
    except FileNotFoundError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(record, dict):
                yield record


def _journal_jobs(run_dir: str) -> Dict[str, Dict]:
    """Per-job admission facts from the shared journal, epoch-fenced the
    way ``replay_journal`` fences terminals (mirroring
    ``obs/trace.py:_journal_facts``, plus the ``cost`` block and request
    kind the cost report needs)."""
    from spark_examples_tpu.serve.journal import (
        iter_journal_records,
        journal_path,
    )

    jobs: Dict[str, Dict] = {}
    for record in iter_journal_records(journal_path(run_dir)):
        job_id = record.get("id")
        if not isinstance(job_id, str):
            continue
        job = jobs.setdefault(
            job_id,
            {
                "trace": None,
                "kind": None,
                "class": None,
                "submitted_unix": None,
                "deadline_unix": None,
                "predicted_seconds": None,
                "cost": None,
                "began": False,
                "stolen": False,
                "lease_epoch": 0,
                "replicas": [],
                "terminals": [],
                "status": None,
            },
        )
        replica = record.get("replica")
        if isinstance(replica, str) and replica not in job["replicas"]:
            job["replicas"].append(replica)
        event = record.get("event")
        if event == "accepted":
            trace = record.get("trace")
            if isinstance(trace, str):
                job["trace"] = trace
            request = record.get("request")
            if isinstance(request, dict):
                kind = request.get("kind")
                if isinstance(kind, str):
                    job["kind"] = kind
            job_class = record.get("job_class")
            if isinstance(job_class, str):
                job["class"] = job_class
            job["submitted_unix"] = record.get("submitted_unix")
            job["deadline_unix"] = record.get("deadline_unix")
            cost = record.get("cost")
            if isinstance(cost, dict):
                job["cost"] = cost
                predicted = cost.get("predicted_seconds")
                if isinstance(predicted, (int, float)) and not isinstance(
                    predicted, bool
                ):
                    job["predicted_seconds"] = float(predicted)
        elif event == "began":
            job["began"] = True
        elif event == "lease":
            epoch = record.get("epoch")
            if isinstance(epoch, int):
                job["lease_epoch"] = max(job["lease_epoch"], epoch)
            if record.get("stolen"):
                job["stolen"] = True
        elif event == "terminal":
            epoch = record.get("epoch")
            job["terminals"].append(
                (
                    epoch if isinstance(epoch, int) else None,
                    record.get("status"),
                )
            )
    for job in jobs.values():
        fence = job["lease_epoch"]
        for epoch, status in job["terminals"]:
            if epoch is None or epoch >= fence:
                job["status"] = status
        del job["terminals"]
    return jobs


def _recorder_events(run_dir: str) -> List[Dict]:
    """Flight-recorder events, ``[]`` when no segments reached disk —
    the report degrades, never fails, on a fleet whose rings were
    lost."""
    try:
        from spark_examples_tpu.obs.recorder import read_segments

        return read_segments(run_dir)
    except Exception:
        return []


def build_fleet_report(run_dir: str) -> Dict:
    """The whole report as one JSON-safe document (the ``--json`` body;
    the text renderer reads the same dict). Raises ``FileNotFoundError``
    when the run dir holds neither a journal nor a calibration ledger."""
    from spark_examples_tpu.serve.journal import journal_path

    have_journal = os.path.exists(journal_path(run_dir))
    ledger_path = calibration_path(run_dir)
    have_ledger = os.path.exists(ledger_path)
    if not have_journal and not have_ledger:
        raise FileNotFoundError(
            f"nothing to report: no journal at {journal_path(run_dir)!r} "
            f"and no calibration ledger at {ledger_path!r}"
        )
    jobs = _journal_jobs(run_dir) if have_journal else {}

    # Join the ledger's measured truth onto the journal's admission
    # facts; ledger rows for jobs the journal compacted away (or a
    # journal lost to the crash) still count in the class quantiles.
    by_class: Dict[str, Dict[str, List[float]]] = {}
    ledger_samples = 0
    for record in _iter_ledger_records(ledger_path):
        measured = record.get("measured_seconds")
        if isinstance(measured, bool) or not isinstance(
            measured, (int, float)
        ):
            continue
        ledger_samples += 1
        # Class quantiles stay done-only (a failed row's wall measures
        # the failure path); the per-job join below takes every row.
        job_class = record.get("job_class")
        if record.get("status") in (None, "done") and isinstance(
            job_class, str
        ):
            lanes = by_class.setdefault(
                job_class, {"wall": [], "queue_wait": []}
            )
            lanes["wall"].append(float(measured))
            queue_wait = record.get("queue_wait_seconds")
            if isinstance(queue_wait, (int, float)) and not isinstance(
                queue_wait, bool
            ):
                lanes["queue_wait"].append(float(queue_wait))
        job = jobs.get(record.get("id") or "")
        if job is not None:
            job["measured_seconds"] = float(measured)
            queue_wait = record.get("queue_wait_seconds")
            if isinstance(queue_wait, (int, float)) and not isinstance(
                queue_wait, bool
            ):
                job["queue_wait_seconds"] = float(queue_wait)
            compile_disposition = record.get("compile")
            if isinstance(compile_disposition, str):
                job["compile"] = compile_disposition

    classes: Dict[str, Dict] = {}
    for job_class, lanes in sorted(by_class.items()):
        block: Dict[str, Dict] = {}
        for lane_name, values in lanes.items():
            ordered = sorted(values)
            if not ordered:
                continue
            block[f"{lane_name}_seconds"] = {
                "count": len(ordered),
                "mean": sum(ordered) / len(ordered),
                "p50": _quantile(ordered, 0.50),
                "p95": _quantile(ordered, 0.95),
                "p99": _quantile(ordered, 0.99),
            }
        classes[job_class] = block

    # The flight recorder fills what the ledger cannot know: a stolen
    # job's queue wait was observed (and durably flushed, pre-kill-point)
    # by the owner that dequeued it, even when that owner died before
    # any terminal row — the job-begin event carries it.
    events = _recorder_events(run_dir)
    for event in events:
        job = jobs.get(event.get("job") or "")
        if job is None or job.get("queue_wait_seconds") is not None:
            continue
        if event.get("name") == "job" and event.get("ph") == "B":
            queue_wait = (event.get("args") or {}).get("queue_wait")
            if isinstance(queue_wait, (int, float)) and not isinstance(
                queue_wait, bool
            ):
                job["queue_wait_seconds"] = float(queue_wait)
    recorder = (
        {
            "events": len(events),
            "replicas": sorted({e["replica"] for e in events}),
        }
        if events
        else None
    )

    statuses: Dict[str, int] = {}
    for job in jobs.values():
        statuses[job["status"] or "unsettled"] = (
            statuses.get(job["status"] or "unsettled", 0) + 1
        )

    # The protocol post-mortem: the SAME protocol_summary fold
    # `graftcheck proto` asserts GP001-GP006 over, run on this fleet's
    # real journal — fence epochs, fenced-vs-effective terminal
    # verdicts, steal counts. One code path for the proof and the
    # report.
    protocol = None
    if have_journal:
        from spark_examples_tpu.serve.journal import (
            iter_journal_records,
            protocol_summary,
        )

        protocol = protocol_summary(
            iter_journal_records(journal_path(run_dir))
        )

    return {
        "run_dir": os.path.abspath(run_dir),
        "jobs": jobs,
        "totals": {
            "journaled": len(jobs),
            "statuses": statuses,
            "stolen": sum(1 for j in jobs.values() if j["stolen"]),
            "began": sum(1 for j in jobs.values() if j["began"]),
            "with_prediction": sum(
                1
                for j in jobs.values()
                if j["predicted_seconds"] is not None
            ),
            "ledger_samples": ledger_samples,
        },
        "classes": classes,
        "calibration": fold_calibration(ledger_path).summary(),
        "recorder": recorder,
        "protocol": protocol,
    }


def _seconds(value) -> str:
    return f"{value:.3f}s" if isinstance(value, (int, float)) else "-"


def render_fleet_report(doc: Dict) -> str:
    """The human form of :func:`build_fleet_report` (stderr-free: the
    report IS the output)."""
    lines: List[str] = [f"fleet report: {doc['run_dir']}"]
    totals = doc["totals"]
    status_text = ", ".join(
        f"{status} {count}"
        for status, count in sorted(totals["statuses"].items())
    )
    lines.append(
        f"journaled jobs: {totals['journaled']}"
        + (f" ({status_text})" if status_text else "")
        + f"; stolen {totals['stolen']}; device-began {totals['began']}; "
        f"predictions {totals['with_prediction']}; "
        f"ledger samples {totals['ledger_samples']}"
    )
    recorder = doc.get("recorder")
    if recorder:
        lines.append(
            f"flight recorder: {recorder['events']} events from "
            f"{len(recorder['replicas'])} replica(s): "
            + ", ".join(recorder["replicas"])
        )
    protocol = doc.get("protocol")
    if protocol:
        proto_totals = protocol["totals"]
        lines.append(
            f"protocol: accepted {proto_totals['accepted']}, settled "
            f"{proto_totals['settled']}, pending "
            f"{proto_totals['pending']}; terminals "
            f"{proto_totals['terminals']} "
            f"({proto_totals['effective_terminals']} effective, "
            f"{proto_totals['fenced_terminals']} fenced); steals "
            f"{proto_totals['steals']}; max lease epoch "
            f"{proto_totals['max_lease_epoch']}"
        )
        for job_id, info in sorted(protocol["jobs"].items()):
            fenced = [t for t in info["terminals"] if not t["effective"]]
            if not fenced and not info["steals"]:
                continue
            # Only the jobs with protocol drama get a line: a fenced
            # terminal is a zombie write the fold absorbed, a steal is
            # a replica takeover — both are what a post-mortem reads
            # this block for.
            fence_text = ", ".join(
                f"{t['status']}@e{t['epoch']}" for t in fenced
            )
            lines.append(
                f"  job {job_id}: fence e{info['fence']} "
                f"owner {info['owner'] or '-'}; steals {info['steals']}"
                + (f"; fenced terminals: {fence_text}" if fenced else "")
            )
    calibration = doc.get("calibration") or {}
    if calibration.get("samples"):
        ratio = calibration.get("ratio")
        lines.append(
            f"calibration: n={calibration['samples']}, ratio "
            + (f"{ratio:.3f}" if isinstance(ratio, (int, float)) else "-")
            + f", predicted mean "
            f"{_seconds(calibration.get('predicted_mean_seconds'))}, "
            f"measured mean "
            f"{_seconds(calibration.get('measured_mean_seconds'))}, "
            f"geometries {len(calibration.get('geometries') or {})}"
        )
    for job_class, block in sorted((doc.get("classes") or {}).items()):
        for lane, label in (
            ("wall_seconds", "wall"),
            ("queue_wait_seconds", "queue wait"),
        ):
            stats = block.get(lane)
            if not stats:
                continue
            lines.append(
                f"class {job_class} {label}: p50 {_seconds(stats['p50'])} "
                f"p95 {_seconds(stats['p95'])} p99 {_seconds(stats['p99'])}"
                f" (n={stats['count']})"
            )
    for job_id, job in sorted((doc.get("jobs") or {}).items()):
        flags = []
        if job["stolen"]:
            flags.append("stolen")
        if job.get("compile"):
            flags.append(job["compile"])
        detail = [
            f"predicted {_seconds(job.get('predicted_seconds'))}",
            f"measured {_seconds(job.get('measured_seconds'))}",
            f"queue wait {_seconds(job.get('queue_wait_seconds'))}",
        ]
        predicted = job.get("predicted_seconds")
        measured = job.get("measured_seconds")
        if (
            isinstance(predicted, (int, float))
            and predicted > 0
            and isinstance(measured, (int, float))
        ):
            detail.append(f"ratio {measured / predicted:.2f}")
        lines.append(
            f"job {job_id} [{job.get('class') or '?'}/"
            f"{job.get('kind') or '?'}] {job.get('status') or 'unsettled'}"
            + (f" ({', '.join(flags)})" if flags else "")
            + f" trace={job.get('trace') or '-'}: "
            + ", ".join(detail)
        )
    return "\n".join(lines)


def report_main(argv: Optional[Sequence[str]] = None) -> int:
    """The ``obs`` CLI verb: ``obs report --run-dir DIR [--json]``.
    Exit 0 on a rendered report, 1 when the run dir has nothing to
    report, 2 on usage errors. Reads only on-disk artifacts — the fleet
    may be long dead."""
    argv = list(argv) if argv is not None else sys.argv[1:]
    if not argv or argv[0] != "report":
        print(
            "usage: python -m spark_examples_tpu obs report "
            "--run-dir DIR [--json]",
            file=sys.stderr,
        )
        return 2
    parser = argparse.ArgumentParser(prog="spark_examples_tpu obs report")
    parser.add_argument(
        "--run-dir",
        required=True,
        help=(
            "The serve fleet's shared run directory (journal + "
            "calibration.jsonl + trace/)."
        ),
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="Emit the structured report document instead of text.",
    )
    ns = parser.parse_args(argv[1:])
    if not os.path.isdir(ns.run_dir):
        print(f"obs report: no run dir {ns.run_dir!r}", file=sys.stderr)
        return 2
    try:
        doc = build_fleet_report(ns.run_dir)
    except FileNotFoundError as e:
        print(f"obs report: {e}", file=sys.stderr)
        return 1
    if ns.json:
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        print(render_fleet_report(doc))
    return 0


__all__ = [
    "build_fleet_report",
    "render_fleet_report",
    "report_main",
]

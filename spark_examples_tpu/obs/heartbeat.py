"""Background heartbeat: a periodic progress line for long runs.

A whole-genome ingest runs for minutes to hours with nothing on the
console between the config echo and the epilogue; the reference's operator
watched the Spark UI's stage progress instead (SURVEY.md §5). The TPU
stand-in is this reporter: a daemon thread that samples the run's
:class:`~spark_examples_tpu.obs.metrics.MetricsRegistry` every
``interval_seconds`` and emits one line to stderr (stdout stays reserved
for the result rows and the machine-read epilogue), e.g.::

    heartbeat[12s]: 1,203,200 sites scanned (98.3k sites/s); \
partitions 34/220 (ETA 67s); prefetch queue 2/2; dispatch in-flight 1; \
device mem 2.1/16.0 GiB

Segments appear only when their metric exists, so every pipeline path
(device-gen, packed, streamed, wire) gets an honest subset. Enabled by
``--heartbeat-seconds N`` (0 = off — the default, so pytest runs and
existing stdout-golden consumers see zero new output).

Well-known metric names sampled (producers register them; see DESIGN.md §9):

- ``ingest_sites_scanned`` (gauge) + the tick-to-tick rate derived from it
- ``ingest_partitions_done`` (gauge, streamed path) or
  ``io_partitions_total`` (counter, per-shard paths) vs
  ``ingest_partitions_planned`` (gauge) — the
  ``--num-reduce-partitions``-bounded shard progress and ETA
- ``prefetch_queue_occupancy`` / ``prefetch_queue_depth`` (gauges)
- ``gramian_inflight_dispatches`` (gauge)
- ``analysis_sites_kept`` vs ``analysis_sites_tested`` (gauges,
  ``analyses/`` pruning runs — the LD kept ratio advances per flushed
  window)
- ``gramian_ring_bytes`` (counter, sharded paths) — cumulative ICI ring
  traffic, the number ``--ring-pack-bits`` cuts 8×
- ``host_peak_rss_bytes`` (function-backed gauge — each tick samples the
  OS high-water mark) vs ``host_static_bound_bytes`` (the
  ``host_peak_bytes`` formula), the host-memory pair ``graftcheck
  hostmem`` cross-validates
- ``serve_queue_depth`` / ``serve_jobs_inflight`` / ``serve_jobs_done``
  (gauges, resident service) — the admission-queue liveness the daemon's
  service heartbeat shows instead of ingest progress
- ``serve_slices`` vs ``serve_slices_busy`` (gauges) — executor-slice
  concurrency (busy == total reads as saturation), and
  ``serve_batches_total``/``serve_batch_jobs_total`` (counters) — the
  continuous-batching yield
- ``serve_replicas_alive`` (gauge) with ``serve_jobs_stolen_total`` /
  ``serve_lease_renewals_total`` (counters) — the multi-replica lease
  substrate's liveness, so a replica daemon's heartbeat shows the pool
  thinning (and its own steals) the moment a peer stops renewing
- ``cost_predicted_mean_seconds`` / ``cost_measured_mean_seconds`` /
  ``cost_calibration_samples`` (function-backed gauges over the folded
  calibration ledger, ``obs/calibration.py``) — the cost observatory's
  predicted-vs-measured segment, e.g. ``cost pred 3.2s / meas 2.9s
  (ratio 0.91, n=17)``
- ``compile_cache_geometry_hits`` / ``..._misses`` (function-backed
  gauges) — the warm-geometry ledger (``utils/cache.py``), the resident
  service's compile-once promise per tick
- device memory from ``jax.local_devices()[0].memory_stats()`` when the
  backend reports it (TPU does; CPU test devices do not).

The thread is a context manager and ``stop()`` is idempotent: the driver
stops it in a ``finally``, so a mid-run exception emits its last heartbeat
and then goes quiet instead of interleaving with the traceback.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional

from spark_examples_tpu.obs.metrics import (
    ANALYSIS_SITES_KEPT,
    ANALYSIS_SITES_TESTED,
    COMPILE_CACHE_GEOMETRY_HITS,
    COMPILE_CACHE_GEOMETRY_MISSES,
    COST_CALIBRATION_SAMPLES,
    COST_MEASURED_MEAN_SECONDS,
    COST_PREDICTED_MEAN_SECONDS,
    GRAMIAN_INFLIGHT_DISPATCHES,
    GRAMIAN_RING_BYTES,
    HOST_PEAK_RSS_BYTES,
    HOST_STATIC_BOUND_BYTES,
    INGEST_PARTITIONS_DONE,
    INGEST_PARTITIONS_PLANNED,
    INGEST_SITES_SCANNED,
    IO_PARTITIONS_TOTAL,
    MetricsRegistry,
    PREFETCH_QUEUE_DEPTH,
    PREFETCH_QUEUE_OCCUPANCY,
    SERVE_BATCH_JOBS,
    SERVE_BATCHES,
    SERVE_FUSED_GROUPS,
    SERVE_FUSED_JOBS,
    SERVE_JOBS_DONE,
    SERVE_JOBS_INFLIGHT,
    SERVE_JOBS_STOLEN,
    SERVE_LEASE_RENEWALS,
    SERVE_QUEUE_DEPTH,
    SERVE_REPLICAS_ALIVE,
    SERVE_SLICES,
    SERVE_SLICES_BUSY,
)


def _bytes_text(count: float) -> str:
    for bound, unit in ((1 << 30, "GiB"), (1 << 20, "MiB"), (1 << 10, "KiB")):
        if count >= bound:
            return f"{count / bound:.1f} {unit}"
    return f"{int(count)} B"


def _device_memory_line() -> Optional[str]:
    """``used/limit GiB`` of the first local device, or ``None`` when the
    backend has no memory stats (CPU) or jax is not initialized yet."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if used is None:
            return None
        gib = 1024.0**3
        if limit:
            return f"device mem {used / gib:.1f}/{limit / gib:.1f} GiB"
        return f"device mem {used / gib:.1f} GiB"
    except Exception:
        return None


def _rate_text(per_second: float) -> str:
    if per_second >= 1e6:
        return f"{per_second / 1e6:.1f}M"
    if per_second >= 1e3:
        return f"{per_second / 1e3:.1f}k"
    return f"{per_second:.1f}"


class Heartbeat:
    """Periodic registry sampler; start()/stop() or use as a context
    manager. ``emit`` is injectable for tests (default: stderr print)."""

    def __init__(
        self,
        interval_seconds: float,
        registry: MetricsRegistry,
        emit: Optional[Callable[[str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if interval_seconds <= 0:
            raise ValueError(
                f"heartbeat interval must be > 0 (0 disables the heartbeat "
                f"at the flag level), got {interval_seconds}"
            )
        self.interval_seconds = float(interval_seconds)
        self.registry = registry
        self._emit = emit if emit is not None else self._print_stderr
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._last_sites: Optional[float] = None
        self.emitted = 0

    @staticmethod
    def _print_stderr(line: str) -> None:
        print(line, file=sys.stderr, flush=True)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._started_at = self._clock()
        self._thread = threading.Thread(
            target=self._run, name="obs-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Idempotent; joins the thread so no line is emitted after this
        returns (the emits-then-stops-cleanly contract on driver error)."""
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout)

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ----------------------------------------------------------------- tick

    def _run(self) -> None:
        while not self._stop.wait(self.interval_seconds):
            try:
                self._emit(self.line())
                self.emitted += 1
            except Exception:
                # A reporting bug must never take down the run; stop
                # rather than spam identical tracebacks every interval.
                return

    def line(self) -> str:
        """One progress line from the current registry state."""
        now = self._clock()
        elapsed = now - (self._started_at if self._started_at is not None else now)
        parts = []

        sites = self.registry.value(INGEST_SITES_SCANNED)
        if sites is not None:
            segment = f"{int(sites):,} sites scanned"
            ref_tick = self._last_tick
            ref_sites = self._last_sites
            if ref_tick is not None and now > ref_tick and ref_sites is not None:
                rate = (sites - ref_sites) / (now - ref_tick)
                if rate >= 0:
                    segment += f" ({_rate_text(rate)} sites/s)"
            self._last_tick, self._last_sites = now, sites
            parts.append(segment)

        # Partition progress: the live streaming-pass gauge when one exists
        # (the streamed path flushes its I/O stats only after the whole
        # pass), else the registry-backed stats counter the per-shard paths
        # advance as they go.
        done = self.registry.value(INGEST_PARTITIONS_DONE)
        if done is None:
            done = self.registry.value(IO_PARTITIONS_TOTAL)
        planned = self.registry.value(INGEST_PARTITIONS_PLANNED)
        if done is not None and planned:
            segment = f"partitions {int(done)}/{int(planned)}"
            if 0 < done < planned and elapsed > 0:
                eta = elapsed * (planned - done) / done
                segment += f" (ETA {eta:.0f}s)"
            parts.append(segment)

        occupancy = self.registry.value(PREFETCH_QUEUE_OCCUPANCY)
        depth = self.registry.value(PREFETCH_QUEUE_DEPTH)
        if occupancy is not None and occupancy == occupancy:  # not NaN
            segment = f"prefetch queue {int(occupancy)}"
            if depth:
                segment += f"/{int(depth)}"
            parts.append(segment)

        in_flight = self.registry.value(GRAMIAN_INFLIGHT_DISPATCHES)
        if in_flight is not None:
            parts.append(f"dispatch in-flight {int(in_flight)}")

        # Per-site analysis progress (analyses/ LD prune): kept vs tested,
        # advanced per flushed window. The tested count alone would repeat
        # the sites-scanned segment, so the pair only appears once a
        # pruning analysis registers its kept gauge.
        kept = self.registry.value(ANALYSIS_SITES_KEPT)
        if kept is not None and kept == kept:
            tested = self.registry.value(ANALYSIS_SITES_TESTED)
            if tested is not None and tested == tested:
                parts.append(
                    f"analysis kept {int(kept):,}/{int(tested):,} sites"
                )

        ring_bytes = self.registry.value(GRAMIAN_RING_BYTES)
        if ring_bytes:
            parts.append(f"ring traffic {_bytes_text(ring_bytes)}")

        # Resident-service liveness (serve/): the daemon registers these
        # in its service registry, so a service heartbeat shows admission
        # state where a batch run's heartbeat shows ingest progress.
        queued = self.registry.value(SERVE_QUEUE_DEPTH)
        if queued is not None and queued == queued:
            segment = f"serve queue {int(queued)}"
            inflight = self.registry.value(SERVE_JOBS_INFLIGHT)
            if inflight is not None and inflight == inflight:
                segment += f" (in-flight {int(inflight)}"
                done = self.registry.value(SERVE_JOBS_DONE)
                if done is not None and done == done:
                    segment += f", done {int(done)}"
                segment += ")"
            parts.append(segment)

        # Executor-slice concurrency (serve/ per-slice workers): how many
        # of the daemon's independent device slices are executing right
        # now — saturation reads as busy == total.
        slices = self.registry.value(SERVE_SLICES)
        if slices is not None and slices == slices and slices > 0:
            busy = self.registry.value(SERVE_SLICES_BUSY)
            if busy is not None and busy == busy:
                parts.append(f"slices {int(busy)}/{int(slices)} busy")

        # Multi-replica liveness (serve/journal.py lease substrate): how
        # many replicas are heartbeating against the shared run dir (self
        # included — a lone 1 reads as "my peers are gone"), plus this
        # replica's steal and lease-renewal counters. Solo daemons export
        # replicas=0 and the segment stays silent.
        replicas = self.registry.value(SERVE_REPLICAS_ALIVE)
        if replicas is not None and replicas == replicas and replicas > 0:
            segment = f"replicas {int(replicas)} alive"
            extras = []
            stolen = self.registry.value(SERVE_JOBS_STOLEN)
            if stolen:
                extras.append(f"stolen {int(stolen)}")
            renewals = self.registry.value(SERVE_LEASE_RENEWALS)
            if renewals:
                extras.append(f"lease renewals {int(renewals)}")
            if extras:
                segment += " (" + ", ".join(extras) + ")"
            parts.append(segment)

        # Continuous-batching yield: dispatch groups that coalesced more
        # than one compatible small job, and the jobs they carried.
        batches = self.registry.value(SERVE_BATCHES)
        if batches:
            batch_jobs = self.registry.value(SERVE_BATCH_JOBS)
            segment = f"batched {int(batches)} groups"
            if batch_jobs:
                segment += f" ({int(batch_jobs)} jobs)"
            parts.append(segment)

        # Fused dispatch yield: batch groups that ran as ONE stacked
        # device program (serve/executor.py:execute_fused_batch), with
        # the mean group size — "fused 3 K-job groups (K≈4.0)" says the
        # one-program-per-group promise is actually engaging.
        fused = self.registry.value(SERVE_FUSED_GROUPS)
        if fused:
            fused_jobs = self.registry.value(SERVE_FUSED_JOBS)
            segment = f"fused {int(fused)} K-job group(s)"
            if fused_jobs:
                segment += f" (K≈{fused_jobs / fused:.1f})"
            parts.append(segment)

        # Cost-calibration segment (obs/calibration.py fold, sampled via
        # the function-backed COST_* gauges the serve daemon registers):
        # mean predicted vs mean measured wall seconds with the learned
        # ratio and the sample count behind it — silent until the first
        # completed job lands in the ledger (the gauges read NaN).
        cost_n = self.registry.value(COST_CALIBRATION_SAMPLES)
        if cost_n is not None and cost_n == cost_n and cost_n > 0:
            predicted = self.registry.value(COST_PREDICTED_MEAN_SECONDS)
            measured = self.registry.value(COST_MEASURED_MEAN_SECONDS)
            if (
                predicted is not None
                and predicted == predicted
                and measured is not None
                and measured == measured
            ):
                segment = (
                    f"cost pred {predicted:.1f}s / meas {measured:.1f}s"
                )
                if predicted > 0:
                    segment += (
                        f" (ratio {measured / predicted:.2f}, "
                        f"n={int(cost_n)})"
                    )
                else:
                    segment += f" (n={int(cost_n)})"
                parts.append(segment)

        # Warm-geometry compile-cache pair (utils/cache.py ledger): the
        # compile-once promise of a resident process, visible per tick.
        hits = self.registry.value(COMPILE_CACHE_GEOMETRY_HITS)
        misses = self.registry.value(COMPILE_CACHE_GEOMETRY_MISSES)
        if (
            hits is not None
            and hits == hits
            and misses is not None
            and misses == misses
        ):
            parts.append(
                f"compile cache {int(hits)} warm/{int(misses)} cold"
            )

        # Host-memory cross-validation pair: each tick SAMPLES the
        # function-backed peak-RSS gauge (graftcheck hostmem's runtime
        # half), shown against the static bound — ALWAYS a real number
        # now (``conf_host_peak_bytes`` is total; a process that never
        # registered the gauge gets the runtime-baseline bound), so an
        # operator watches the headroom shrink long before an OOM.
        peak_rss = self.registry.value(HOST_PEAK_RSS_BYTES)
        if peak_rss is not None and peak_rss == peak_rss and peak_rss > 0:
            bound = self.registry.value(HOST_STATIC_BOUND_BYTES)
            if bound is None or bound != bound or bound <= 0:
                from spark_examples_tpu.parallel.mesh import (
                    HOST_RUNTIME_BASELINE_BYTES,
                )

                bound = HOST_RUNTIME_BASELINE_BYTES
            parts.append(
                f"host rss peak {_bytes_text(peak_rss)}"
                f"/{_bytes_text(bound)} bound"
            )

        memory = _device_memory_line()
        if memory is not None:
            parts.append(memory)

        if not parts:
            parts.append("no progress metrics registered yet")
        return f"heartbeat[{elapsed:.0f}s]: " + "; ".join(parts)


__all__ = ["Heartbeat"]

"""Per-job cost predictions: the admission-time estimate the fleet audits.

The serve fleet already *computes* device-free cost facts per job — the
collective-schedule simulator's critical-path seconds (``graftcheck
sched`` GS005), the TOTAL host-memory bound (``graftcheck hostmem``),
ring bytes per flush — but until this module none of them were ever
recorded ON the job. :class:`CostPrediction` is that record: a small,
JSON-round-trippable envelope stamped at admission into the job doc, the
journal's ``accepted`` record (so it survives compaction, restart, and
replica steal exactly like the trace id), and the per-job manifest.

The prediction combines two sources:

- **link transfer** — the sched simulator's critical-path seconds, when
  the configuration proves a ring schedule on a declared topology. This
  term is exact for what it models, but it models ONLY ppermute traffic:
  a single-device job has no collectives and would predict ~0.
- **compute throughput** — a deliberately coarse sites-per-second model
  (:data:`SITES_PER_SECOND`) plus fixed dispatch overhead and a cold-
  compile penalty. Coarse is fine: the calibration ledger
  (``obs/calibration.py``) learns the per-geometry measured/predicted
  ratio, so the model only has to be *monotone and positive* — the
  learned ratio absorbs the constant.

The floor (:data:`MIN_PREDICTED_SECONDS`) keeps every prediction
strictly positive, which makes deadline-feasibility deterministic: a
submitted ``deadline_seconds`` below the floor is infeasible for ANY
job, so the 413 path needs no special empty-model case.

No imports from ``check/`` or ``serve/`` here — this module sits below
both (plan builds predictions, serve stamps and measures them), and a
cycle would force lazy imports everywhere above.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional

#: Coarse device throughput for the compute term (candidate sites per
#: second). Intentionally conservative next to the measured ~13.5 M
#: sites/s/chip whole-genome number (DESIGN.md §7): the calibration
#: ledger's per-geometry ratio corrects the constant, and a conservative
#: base errs toward over-prediction — the safe direction for deadline
#: feasibility (reject-early beats accept-then-expire).
SITES_PER_SECOND: float = 2_000_000.0

#: Bytes/second proxy used when the site count has no static bound
#: (file/REST cohorts): the host-memory bound is TOTAL for every source,
#: so ``host_peak_bytes`` over a nominal ingest bandwidth gives a finite,
#: monotone stand-in for the compute term.
HOST_BYTES_PER_SECOND: float = 200e6

#: Fixed per-job dispatch/finalize overhead (queue handoff, manifest
#: write, result marshalling) — the latency floor even a trivial warm
#: job pays.
DISPATCH_OVERHEAD_SECONDS: float = 0.05

#: One-time penalty when the geometry ledger says this compile
#: fingerprint has never been built in this process fleet.
COLD_COMPILE_SECONDS: float = 1.5

#: Hard positive floor on every prediction (see module docstring).
MIN_PREDICTED_SECONDS: float = 0.05

#: The two compile expectations a prediction can carry.
COMPILE_WARM = "warm"
COMPILE_COLD = "cold"


@dataclass
class CostPrediction:
    """One job's admission-time cost estimate, JSON-round-trippable.

    ``predicted_seconds`` is the headline number (floored, penalty
    included); the remaining fields are its provenance, kept so the
    post-mortem report and the calibration fold can attribute error to
    the right term instead of a single opaque scalar.
    """

    predicted_seconds: float
    kind: str = "pca"
    fingerprint: Optional[str] = None
    compile: str = COMPILE_COLD
    compute_seconds: float = 0.0
    sched_seconds: Optional[float] = None
    sites: Optional[int] = None
    host_peak_bytes: Optional[int] = None
    ring_bytes_per_flush: Optional[int] = None
    calibrated_seconds: Optional[float] = None
    calibration_ratio: Optional[float] = None
    calibration_samples: int = 0

    def to_dict(self) -> Dict[str, object]:
        """The additive envelope block (job doc / journal / manifest)."""
        out: Dict[str, object] = {
            "predicted_seconds": float(self.predicted_seconds),
            "kind": self.kind,
            "compile": self.compile,
            "compute_seconds": float(self.compute_seconds),
        }
        if self.fingerprint is not None:
            out["fingerprint"] = self.fingerprint
        if self.sched_seconds is not None:
            out["sched_seconds"] = float(self.sched_seconds)
        if self.sites is not None:
            out["sites"] = int(self.sites)
        if self.host_peak_bytes is not None:
            out["host_peak_bytes"] = int(self.host_peak_bytes)
        if self.ring_bytes_per_flush is not None:
            out["ring_bytes_per_flush"] = int(self.ring_bytes_per_flush)
        if self.calibrated_seconds is not None:
            out["calibrated_seconds"] = float(self.calibrated_seconds)
        if self.calibration_ratio is not None:
            out["calibration_ratio"] = float(self.calibration_ratio)
        if self.calibration_samples:
            out["calibration_samples"] = int(self.calibration_samples)
        return out

    @classmethod
    def from_dict(cls, doc: Mapping) -> Optional["CostPrediction"]:
        """Parse a stamped prediction back; ``None`` on junk — a torn or
        foreign ``cost`` block must never kill a journal replay."""
        try:
            predicted = float(doc["predicted_seconds"])
        except (KeyError, TypeError, ValueError):
            return None
        if not (predicted == predicted and predicted >= 0):
            return None

        def _opt_float(key):
            value = doc.get(key)
            return None if value is None else float(value)

        def _opt_int(key):
            value = doc.get(key)
            return None if value is None else int(value)

        try:
            return cls(
                predicted_seconds=predicted,
                kind=str(doc.get("kind") or "pca"),
                fingerprint=(
                    str(doc["fingerprint"])
                    if doc.get("fingerprint") is not None
                    else None
                ),
                compile=(
                    COMPILE_WARM
                    if doc.get("compile") == COMPILE_WARM
                    else COMPILE_COLD
                ),
                compute_seconds=float(doc.get("compute_seconds") or 0.0),
                sched_seconds=_opt_float("sched_seconds"),
                sites=_opt_int("sites"),
                host_peak_bytes=_opt_int("host_peak_bytes"),
                ring_bytes_per_flush=_opt_int("ring_bytes_per_flush"),
                calibrated_seconds=_opt_float("calibrated_seconds"),
                calibration_ratio=_opt_float("calibration_ratio"),
                calibration_samples=int(doc.get("calibration_samples") or 0),
            )
        except (TypeError, ValueError):
            return None

    @property
    def best_estimate_seconds(self) -> float:
        """The number deadline feasibility compares against: the
        calibrated estimate when the ledger has seen this geometry, the
        raw model otherwise."""
        if self.calibrated_seconds is not None:
            return self.calibrated_seconds
        return self.predicted_seconds


def estimate_seconds(
    *,
    sites: Optional[int],
    host_peak_bytes: Optional[int],
    sched_seconds: Optional[float],
    cold: bool,
) -> Dict[str, float]:
    """The model itself, pure arithmetic over geometry facts: compute
    term from the static site count (bytes-proxy fallback), max'd with
    the schedule simulator's link term (compute and transfer overlap —
    the double-buffered feed), plus overhead and the cold penalty.
    Returns ``{"compute_seconds", "predicted_seconds"}``."""
    if sites is not None and sites > 0:
        compute = float(sites) / SITES_PER_SECOND
    elif host_peak_bytes is not None and host_peak_bytes > 0:
        compute = float(host_peak_bytes) / HOST_BYTES_PER_SECOND
    else:
        compute = 0.0
    body = max(compute, float(sched_seconds or 0.0))
    predicted = DISPATCH_OVERHEAD_SECONDS + body
    if cold:
        predicted += COLD_COMPILE_SECONDS
    return {
        "compute_seconds": compute,
        "predicted_seconds": max(predicted, MIN_PREDICTED_SECONDS),
    }


__all__ = [
    "COLD_COMPILE_SECONDS",
    "COMPILE_COLD",
    "COMPILE_WARM",
    "CostPrediction",
    "DISPATCH_OVERHEAD_SECONDS",
    "HOST_BYTES_PER_SECOND",
    "MIN_PREDICTED_SECONDS",
    "SITES_PER_SECOND",
    "estimate_seconds",
]

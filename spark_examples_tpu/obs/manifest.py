"""Machine-readable run manifest (``--metrics-json PATH``).

The end-of-run epilogue used to be four print-only reports (I/O stats,
stage timings, the overlap line, the TSV); machines re-ran the pipeline
and hand-rolled their own dicts (``bench.py``). The manifest is the
structured superset: one schema-versioned JSON document with the config
echo, the hierarchical span tree, every registry metric, the I/O stats
block (numerically identical to the printed report — both read the same
registry), the ingest-overlap accounting, and compile-cache state.

Schema: ``{"id": "spark-examples-tpu/run-manifest", "version": 2}``.
:func:`validate_manifest` is the hand-rolled structural validator (no
jsonschema dependency in the image) used by tests and the ``ci.sh`` smoke
stage; bump ``MANIFEST_VERSION`` and extend the validator together.

Version history: v2 added the required ``host_memory`` block —
``peak_rss_bytes`` (measured OS high-water mark) next to
``static_bound_bytes`` (``parallel/mesh.py:host_peak_bytes``, null when
the configured ingest path is O(file)), the pair ``graftcheck hostmem``
cross-validates and ``bench.py`` reports as host-memory headroom. Still
v2 (additive): the optional ``gramian_exactness`` block — ``entry_max``
(measured max |accumulator entry|, ``--check-ranges`` debug sampling)
next to ``static_entry_bound`` (the conversion trigger's own projection,
proven conservative by ``graftcheck ranges`` GR005); null on runs without
the sampling, so existing consumers are untouched. Still v2 (additive):
``compile_cache`` gained ``geometry_hits``/``geometry_misses`` — the
process-wide warm-geometry ledger (``utils/cache.py``), so a served job's
manifest records whether its geometry was already compiled in the
resident daemon. Still v2 (additive): the optional ``resume`` block —
``checkpoint_sites`` (the Gramian-checkpoint cursor this run started
from), ``sites_skipped`` (ingest rows the resume fast-forward consumed
without device work), ``faults_injected`` (deterministic faults fired
in-process, ``utils/faults.py``); present exactly when Gramian
checkpointing/resume was active (``--gramian-checkpoint-dir`` /
``--resume-from``), null otherwise. Still v2 (additive): the optional
``analysis`` block — ``{kind, sites_kept, sites_tested}`` — present on
``analyses/`` runs (GRM/kinship, windowed LD pruning, association scan),
so their manifests are self-describing next to PCA's: ``kind`` names the
analysis, ``sites_tested`` the per-site rows it consumed, ``sites_kept``
the surviving count for pruning analyses (null where keeping is not the
analysis's question). Null on PCA runs, so existing consumers are
untouched. Still v2 (additive): the optional ``schedule`` block —
``{kind, hosts, devices_per_host, predicted_ring_bytes,
measured_ring_bytes, predicted_ici_bytes, predicted_dcn_bytes}`` —
present on sharded-strategy runs: which reduction schedule ran
(``--reduce-schedule``: ``flat`` or ``hier``), its host-major topology
factorization, and the STATIC ring-byte projection next to the
per-flush-accounted total — the predicted-vs-measured pair ``bench.py``
reports so BENCH rounds catch formula drift (``graftcheck sched`` proves
the same formulas against the traced kernel jaxprs). Null on dense/host
runs. Still v2 (additive): the optional ``conformance`` block —
``{prover: {measured, proven, ok} | null}`` for ``hostmem`` (peak RSS vs
``host_peak_bytes``), ``sched`` (accounted ring bytes vs the schedule's
static projection), and ``ranges`` (max |Gramian entry| vs the
GR005-proven projection) — the prover-conformance telemetry the driver's
epilogue registers (``obs/metrics.py:record_prover_conformance``); ``ok``
is the measured<=proven verdict (null when no bound was provable). Null
on runs without conformance telemetry, so existing consumers are
untouched. Still v2 (additive): the optional ``cost`` block —
``{predicted_seconds, measured_seconds, queue_wait_seconds, compile}``
— stamped by the serve daemon onto a completed job's manifest
(``serve/daemon.py:_stamp_manifest_cost``): the admission-time cost
prediction (``obs/costmodel.py``) next to the measured wall clock and
queue wait, with ``compile`` naming the observed ``warm``/``cold``
disposition; extra prediction detail (``fingerprint``,
``calibrated_seconds``, ...) may ride along. Null on batch runs, so
existing consumers are untouched.

Multi-host: under ``jax.distributed`` each process carries per-process
I/O counters. :func:`build_run_manifest` aggregates them across processes
through :func:`spark_examples_tpu.parallel.multihost.aggregate_host_counts`
(a real collective over the global mesh) into ``multihost.io_stats_global``
— every process writes the same global totals, so stats parity holds for
whichever process's manifest a scheduler collects.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Mapping, Optional

MANIFEST_ID = "spark-examples-tpu/run-manifest"
MANIFEST_VERSION = 2

#: The I/O stats fields, in report order (``pipeline/stats.py.__str__``).
IO_STAT_FIELDS = (
    "partitions",
    "reference_bases",
    "variants",
    "requests",
    "unsuccessful_responses",
    "io_exceptions",
    "io_retries",
)

#: IO-stat fields added AFTER schema v2 shipped: every new writer emits
#: them (``pipeline/stats.py:as_dict``), but the validator treats them as
#: optional so archived v2 manifests stay valid — the additive contract.
OPTIONAL_IO_STAT_FIELDS = frozenset({"io_retries"})


def _json_safe(value):
    """Config echo must serialize whatever a conf dataclass carries."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if isinstance(value, Mapping):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if dataclasses.is_dataclass(value):
        return _json_safe(dataclasses.asdict(value))
    return repr(value)


def _compile_cache_block() -> Optional[Dict]:
    """Persistent compile-cache attribution (cold vs warm), mirroring
    ``bench.py``'s reading of the config value ``utils/cache.py`` sets —
    plus the process-wide warm-geometry ledger counts (v2-additive:
    ``geometry_hits``/``geometry_misses``), so a served run's manifest
    records whether its geometry was already compiled in this process."""
    from spark_examples_tpu.utils.cache import compile_cache_stats

    hits, misses = compile_cache_stats()
    directory, entries = None, 0
    try:
        import jax

        directory = jax.config.jax_compilation_cache_dir or None
        if directory:
            entries = len(os.listdir(directory))
    except Exception:
        entries = 0
    return {
        "dir": directory,
        "entries": entries,
        "geometry_hits": hits,
        "geometry_misses": misses,
    }


def _host_memory_block(registry=None) -> Dict:
    """The v2 ``host_memory`` block: measured peak RSS (read directly from
    the OS so every manifest carries it, registry or not) next to the
    static bound. The bound is ALWAYS a real positive number now —
    ``check/hostmem.py:conf_host_peak_bytes`` is total, the driver's
    gauge always carries it, and a manifest written outside a driver run
    (no registry, or the gauge missing) falls back to the runtime
    baseline bound, which is what such a process is actually bounded by."""
    from spark_examples_tpu.obs.metrics import (
        HOST_STATIC_BOUND_BYTES,
        read_host_peak_rss_bytes,
    )
    from spark_examples_tpu.parallel.mesh import HOST_RUNTIME_BASELINE_BYTES

    bound = HOST_RUNTIME_BASELINE_BYTES
    if registry is not None:
        value = registry.value(HOST_STATIC_BOUND_BYTES)
        if value is not None and value == value and value > 0:
            bound = int(value)
    peak = read_host_peak_rss_bytes()
    return {
        "peak_rss_bytes": int(peak) if peak is not None else None,
        "static_bound_bytes": bound,
    }


def _gramian_exactness_block(registry) -> Optional[Dict]:
    """The v2-ADDITIVE ``gramian_exactness`` block (``--check-ranges``):
    measured max |accumulator entry| next to the statically-projected bound
    the conversion trigger maintains — present only when the debug sampling
    ran (the gauges exist), so manifests of normal runs are unchanged."""
    from spark_examples_tpu.obs.metrics import (
        GRAMIAN_ENTRY_MAX,
        GRAMIAN_STATIC_ENTRY_BOUND,
    )

    if registry is None:
        return None
    entry_max = registry.value(GRAMIAN_ENTRY_MAX)
    if entry_max is None or entry_max != entry_max:
        return None
    bound = registry.value(GRAMIAN_STATIC_ENTRY_BOUND)
    return {
        "entry_max": int(entry_max),
        "static_entry_bound": (
            int(bound) if bound is not None and bound == bound else None
        ),
    }


def _process_block() -> Dict:
    try:
        import jax

        return {"index": int(jax.process_index()), "count": int(jax.process_count())}
    except Exception:
        return {"index": 0, "count": 1}


def build_manifest(
    config: Optional[Mapping] = None,
    spans: Optional[List[Dict]] = None,
    metrics: Optional[Dict] = None,
    io_stats: Optional[Dict] = None,
    overlap: Optional[Dict] = None,
    multihost: Optional[Dict] = None,
    host_memory: Optional[Dict] = None,
    gramian_exactness: Optional[Dict] = None,
    resume: Optional[Dict] = None,
    analysis: Optional[Dict] = None,
    schedule: Optional[Dict] = None,
    conformance: Optional[Dict] = None,
    cost: Optional[Dict] = None,
) -> Dict:
    """Assemble a manifest from already-snapshotted parts (the low-level
    form; :func:`build_run_manifest` snapshots a live driver). The
    ``host_memory`` block defaults to a fresh OS sample with no static
    bound, so hand-assembled manifests stay schema-valid;
    ``gramian_exactness`` (v2-additive) stays null unless ``--check-ranges``
    sampling ran; ``resume`` (v2-additive) stays null unless Gramian
    checkpointing/resume was active; ``analysis`` (v2-additive) stays null
    on PCA runs and carries ``{kind, sites_kept, sites_tested}`` on
    ``analyses/`` runs."""
    return {
        "schema": {"id": MANIFEST_ID, "version": MANIFEST_VERSION},
        "created_unix": time.time(),
        "config": _json_safe(dict(config) if config else {}),
        "spans": spans or [],
        "metrics": metrics or {},
        "io_stats": io_stats,
        "overlap": overlap,
        "host_memory": (
            host_memory if host_memory is not None else _host_memory_block()
        ),
        "gramian_exactness": gramian_exactness,
        "resume": resume,
        "analysis": analysis,
        "schedule": schedule,
        "conformance": conformance,
        "cost": cost,
        "compile_cache": _compile_cache_block(),
        "process": _process_block(),
        "multihost": multihost,
    }


def build_run_manifest(conf=None, spans=None, registry=None, io_stats=None,
                       overlap=None, resume=None, analysis=None,
                       schedule=None) -> Dict:
    """Snapshot a live run: ``conf`` (dataclass or mapping), a
    :class:`~spark_examples_tpu.obs.spans.SpanRecorder`, a
    :class:`~spark_examples_tpu.obs.metrics.MetricsRegistry`, the driver's
    ``VariantsDatasetStats`` (or ``None`` when stats are disabled), the
    structured overlap dict from ``PrefetchIterator.overlap_stats()``,
    the checkpoint/resume accounting dict (``None`` when Gramian
    checkpointing was not active), and the per-site analysis block
    (``None`` on PCA runs; ``analyses/`` passes its
    ``{kind, sites_kept, sites_tested}``)."""
    config = (
        dataclasses.asdict(conf)
        if dataclasses.is_dataclass(conf)
        else dict(conf or {})
    )
    stats_block = io_stats.as_dict() if io_stats is not None else None
    multihost_block = None
    process = _process_block()
    if stats_block is not None and process["count"] > 1:
        from spark_examples_tpu.parallel.multihost import aggregate_host_counts

        totals = aggregate_host_counts(
            [stats_block[f] for f in IO_STAT_FIELDS]
        )
        multihost_block = {
            "process_count": process["count"],
            "io_stats_global": dict(zip(IO_STAT_FIELDS, totals)),
        }
    conf_block = None
    if registry is not None:
        from spark_examples_tpu.obs.metrics import conformance_block

        conf_block = conformance_block(registry)
    return build_manifest(
        config=config,
        spans=spans.as_list() if spans is not None else [],
        metrics=registry.as_dict() if registry is not None else {},
        io_stats=stats_block,
        overlap=overlap,
        multihost=multihost_block,
        host_memory=_host_memory_block(registry),
        gramian_exactness=_gramian_exactness_block(registry),
        resume=resume,
        analysis=analysis,
        schedule=schedule,
        conformance=conf_block,
    )


# ------------------------------------------------------------------ validate


def validate_manifest(doc) -> List[str]:
    """Structural validation; returns the list of problems (empty = valid).

    Checks schema identity/version, required top-level keys, the span tree
    shape (recursively), the metrics export shape, and the I/O stats block
    fields — the contract ``bench.py`` and the CI smoke stage consume."""
    errors: List[str] = []
    if not isinstance(doc, Mapping):
        return ["manifest is not a JSON object"]

    schema = doc.get("schema")
    if not isinstance(schema, Mapping):
        errors.append("missing 'schema' object")
    else:
        if schema.get("id") != MANIFEST_ID:
            errors.append(f"schema.id {schema.get('id')!r} != {MANIFEST_ID!r}")
        if schema.get("version") != MANIFEST_VERSION:
            errors.append(
                f"schema.version {schema.get('version')!r} != {MANIFEST_VERSION}"
            )

    for key, kind in (
        ("created_unix", (int, float)),
        ("config", Mapping),
        ("spans", list),
        ("metrics", Mapping),
        ("process", Mapping),
    ):
        if key not in doc:
            errors.append(f"missing {key!r}")
        elif not isinstance(doc[key], kind):
            errors.append(f"{key!r} has wrong type {type(doc[key]).__name__}")

    def check_span(span, path: str) -> None:
        if not isinstance(span, Mapping):
            errors.append(f"span at {path} is not an object")
            return
        if not isinstance(span.get("name"), str):
            errors.append(f"span at {path} missing string 'name'")
        seconds = span.get("seconds")
        if seconds is not None and (
            not isinstance(seconds, (int, float)) or seconds < 0
        ):
            errors.append(f"span {span.get('name')!r} has bad seconds {seconds!r}")
        if not isinstance(span.get("synced"), bool):
            errors.append(f"span {span.get('name')!r} missing bool 'synced'")
        children = span.get("children")
        if not isinstance(children, list):
            errors.append(f"span {span.get('name')!r} missing list 'children'")
        else:
            for i, child in enumerate(children):
                check_span(child, f"{path}/{span.get('name')}[{i}]")

    for i, span in enumerate(doc.get("spans") or []):
        check_span(span, f"spans[{i}]")

    metrics = doc.get("metrics")
    if isinstance(metrics, Mapping):
        for name, family in metrics.items():
            if not isinstance(family, Mapping):
                errors.append(f"metric {name!r} is not an object")
                continue
            if family.get("type") not in ("counter", "gauge", "histogram"):
                errors.append(f"metric {name!r} has bad type {family.get('type')!r}")
            if not isinstance(family.get("values"), list):
                errors.append(f"metric {name!r} missing list 'values'")

    io_stats = doc.get("io_stats")
    if io_stats is not None:
        if not isinstance(io_stats, Mapping):
            errors.append("'io_stats' is neither null nor an object")
        else:
            for field in IO_STAT_FIELDS:
                if field in OPTIONAL_IO_STAT_FIELDS and field not in io_stats:
                    continue
                if not isinstance(io_stats.get(field), int):
                    errors.append(f"io_stats.{field} missing or not an int")

    overlap = doc.get("overlap")
    if overlap is not None and not isinstance(overlap, Mapping):
        errors.append("'overlap' is neither null nor an object")

    exactness = doc.get("gramian_exactness")
    if exactness is not None:
        if not isinstance(exactness, Mapping):
            errors.append("'gramian_exactness' is neither null nor an object")
        else:
            for field in ("entry_max", "static_entry_bound"):
                value = exactness.get(field, "absent")
                if value == "absent":
                    errors.append(f"gramian_exactness.{field} missing")
                elif value is not None and (
                    not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < 0
                ):
                    errors.append(
                        f"gramian_exactness.{field} is neither null nor a "
                        f"non-negative int: {value!r}"
                    )

    resume = doc.get("resume")
    if resume is not None:
        if not isinstance(resume, Mapping):
            errors.append("'resume' is neither null nor an object")
        else:
            for field in (
                "checkpoint_sites",
                "sites_skipped",
                "faults_injected",
            ):
                value = resume.get(field, "absent")
                if (
                    value == "absent"
                    or not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < 0
                ):
                    errors.append(
                        f"resume.{field} missing or not a non-negative "
                        f"int: {value!r}"
                    )

    analysis = doc.get("analysis")
    if analysis is not None:
        if not isinstance(analysis, Mapping):
            errors.append("'analysis' is neither null nor an object")
        else:
            kind = analysis.get("kind")
            if not isinstance(kind, str) or not kind:
                errors.append(
                    f"analysis.kind missing or not a non-empty string: "
                    f"{kind!r}"
                )
            for field in ("sites_kept", "sites_tested"):
                value = analysis.get(field, "absent")
                if value == "absent":
                    errors.append(f"analysis.{field} missing")
                elif value is not None and (
                    not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < 0
                ):
                    errors.append(
                        f"analysis.{field} is neither null nor a "
                        f"non-negative int: {value!r}"
                    )

    conformance = doc.get("conformance")
    if conformance is not None:
        if not isinstance(conformance, Mapping):
            errors.append("'conformance' is neither null nor an object")
        else:
            for prover, pair in conformance.items():
                if prover not in ("hostmem", "sched", "ranges"):
                    errors.append(
                        f"conformance names unknown prover {prover!r}"
                    )
                    continue
                if pair is None:
                    continue
                if not isinstance(pair, Mapping):
                    errors.append(
                        f"conformance.{prover} is neither null nor an object"
                    )
                    continue
                measured = pair.get("measured", "absent")
                if (
                    measured == "absent"
                    or not isinstance(measured, int)
                    or isinstance(measured, bool)
                    or measured < 0
                ):
                    errors.append(
                        f"conformance.{prover}.measured missing or not a "
                        f"non-negative int: {measured!r}"
                    )
                proven = pair.get("proven", "absent")
                if proven == "absent" or (
                    proven is not None
                    and (
                        not isinstance(proven, int)
                        or isinstance(proven, bool)
                        or proven < 0
                    )
                ):
                    errors.append(
                        f"conformance.{prover}.proven is neither null nor "
                        f"a non-negative int: {proven!r}"
                    )
                ok = pair.get("ok", "absent")
                if ok == "absent" or (
                    ok is not None and not isinstance(ok, bool)
                ):
                    errors.append(
                        f"conformance.{prover}.ok is neither null nor a "
                        f"bool: {ok!r}"
                    )

    cost = doc.get("cost")
    if cost is not None:
        if not isinstance(cost, Mapping):
            errors.append("'cost' is neither null nor an object")
        else:
            for field in (
                "predicted_seconds",
                "measured_seconds",
                "queue_wait_seconds",
            ):
                value = cost.get(field, "absent")
                if (
                    value == "absent"
                    or isinstance(value, bool)
                    or not isinstance(value, (int, float))
                    or value != value
                    or value < 0
                ):
                    errors.append(
                        f"cost.{field} missing or not a non-negative "
                        f"number: {value!r}"
                    )
            compile_disposition = cost.get("compile")
            if compile_disposition not in ("warm", "cold"):
                errors.append(
                    f"cost.compile is neither 'warm' nor 'cold': "
                    f"{compile_disposition!r}"
                )

    schedule = doc.get("schedule")
    if schedule is not None:
        if not isinstance(schedule, Mapping):
            errors.append("'schedule' is neither null nor an object")
        else:
            kind = schedule.get("kind")
            if kind not in ("flat", "hier"):
                errors.append(
                    f"schedule.kind is neither 'flat' nor 'hier': {kind!r}"
                )
            for field in (
                "hosts",
                "devices_per_host",
                "predicted_ring_bytes",
                "measured_ring_bytes",
                "predicted_ici_bytes",
                "predicted_dcn_bytes",
            ):
                value = schedule.get(field, "absent")
                if (
                    value == "absent"
                    or not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < 0
                ):
                    errors.append(
                        f"schedule.{field} missing or not a non-negative "
                        f"int: {value!r}"
                    )

    host_memory = doc.get("host_memory")
    if not isinstance(host_memory, Mapping):
        errors.append("missing 'host_memory' object (schema v2)")
    else:
        value = host_memory.get("peak_rss_bytes", "absent")
        if value == "absent":
            errors.append("host_memory.peak_rss_bytes missing")
        elif value is not None and (
            not isinstance(value, int) or isinstance(value, bool) or value < 0
        ):
            errors.append(
                f"host_memory.peak_rss_bytes is neither null nor a "
                f"non-negative int: {value!r}"
            )
        # static_bound_bytes is NOT nullable: the bound resolver is
        # total, so a manifest claiming "no bound" is a schema error.
        bound = host_memory.get("static_bound_bytes", "absent")
        if (
            bound == "absent"
            or not isinstance(bound, int)
            or isinstance(bound, bool)
            or bound <= 0
        ):
            errors.append(
                f"host_memory.static_bound_bytes missing or not a "
                f"positive int: {bound!r}"
            )
    return errors


# ----------------------------------------------------------------------- I/O


def write_manifest(path: str, doc: Mapping) -> None:
    """Write atomically (rename) so a crashed run never leaves a truncated
    manifest for a scheduler to half-parse. The temp name is per-process:
    multi-host processes pointed at one shared path must not interleave
    writes into a common ``.tmp`` — last rename wins cleanly instead."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)


def read_manifest(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def manifest_metric_value(
    doc: Mapping, name: str, labels: Optional[Mapping[str, str]] = None, default=None
):
    """Read one metric series out of a manifest (the consumer-side mirror
    of ``MetricsRegistry.value`` — what ``bench.py`` uses)."""
    family = (doc.get("metrics") or {}).get(name)
    if not family:
        return default
    want = {k: str(v) for k, v in (labels or {}).items()}
    for entry in family.get("values", []):
        if entry.get("labels", {}) == want:
            if "value" in entry:
                return entry["value"]
            # Histogram series: the snapshot (buckets/sum/count), labels
            # stripped — a well-defined shape rather than the raw entry.
            return {k: v for k, v in entry.items() if k != "labels"}
    return default


__all__ = [
    "MANIFEST_ID",
    "MANIFEST_VERSION",
    "IO_STAT_FIELDS",
    "OPTIONAL_IO_STAT_FIELDS",
    "build_manifest",
    "build_run_manifest",
    "validate_manifest",
    "write_manifest",
    "read_manifest",
    "manifest_metric_value",
]

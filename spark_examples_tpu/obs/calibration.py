"""Crash-durable calibration ledger: predicted-vs-measured, per geometry.

The cost model (``obs/costmodel.py``) is deliberately coarse; this
ledger is what makes it honest. Every completed serve job appends ONE
JSON line — its compile fingerprint, predicted seconds, measured wall
seconds, queue wait, warm/cold — to ``<run_dir>/calibration.jsonl``,
and the fold learns the measured/predicted ratio PER GEOMETRY (keyed by
``utils/cache.py:compile_fingerprint``, the same key the warm ledger
uses), so ``calibrated_estimate`` multiplies a fresh prediction by what
this exact compiled program actually cost last time.

Durability contract (the journal's, reused):

- **appends** are ``O_APPEND`` + ``fsync`` per record — a ``kill -9``
  loses at most the line being written;
- **the fold is torn-tail-tolerant**: an unparseable line is skipped
  (by the append protocol it can only be a crashed writer's last line);
- **mergeable across replicas**: N replica daemons append to the ONE
  file in the shared run dir (``O_APPEND`` writes of a single short
  line are atomic enough on POSIX for line-grained interleave; the
  fold is order-insensitive), so any replica's fold — and the offline
  ``obs report`` — sees the whole fleet's samples.

Quantile summaries come from a DETERMINISTIC bounded reservoir
(:class:`_Reservoir`): when full it drops every other element and
doubles its sampling stride — no randomness (repo-wide determinism
rule), bounded memory, and the kept elements remain an evenly-spaced
thinning of the observation stream.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from spark_examples_tpu.obs.costmodel import COMPILE_COLD, COMPILE_WARM

#: Ledger filename under the (shared) service run directory.
CALIBRATION_BASENAME = "calibration.jsonl"

#: Max kept samples per reservoir before stride-doubling.
RESERVOIR_CAPACITY = 256

#: Calibration ratios are only trusted once a geometry has this many
#: samples; below it ``calibrated_estimate`` returns the raw prediction
#: (ratio 1.0) — one outlier job must not poison admission decisions.
MIN_CALIBRATION_SAMPLES = 1


def calibration_path(run_dir: str) -> str:
    return os.path.join(run_dir, CALIBRATION_BASENAME)


class _Reservoir:
    """Deterministic stride-thinning reservoir: keeps every ``stride``-th
    observation, halving the kept set and doubling the stride when full.
    The kept samples are an evenly-spaced subsample of the stream —
    biased only by phase, never by value, and fully reproducible."""

    def __init__(self, capacity: int = RESERVOIR_CAPACITY):
        self.capacity = max(2, int(capacity))
        self.stride = 1
        self.seen = 0
        self.samples: List[float] = []

    def add(self, value: float) -> None:
        if self.seen % self.stride == 0:
            if len(self.samples) >= self.capacity:
                self.samples = self.samples[::2]
                self.stride *= 2
                if self.seen % self.stride != 0:
                    self.seen += 1
                    return
            self.samples.append(float(value))
        self.seen += 1

    def quantile(self, q: float) -> Optional[float]:
        if not self.samples:
            return None
        ordered = sorted(self.samples)
        rank = min(max(float(q), 0.0), 1.0) * (len(ordered) - 1)
        low = int(rank)
        high = min(low + 1, len(ordered) - 1)
        fraction = rank - low
        return ordered[low] * (1.0 - fraction) + ordered[high] * fraction


@dataclass
class GeometryCalibration:
    """The fold of one compile fingerprint's completed jobs."""

    fingerprint: str
    kind: Optional[str] = None
    n: int = 0
    predicted_sum: float = 0.0
    measured_sum: float = 0.0
    queue_wait_sum: float = 0.0
    cold_n: int = 0
    measured: _Reservoir = field(default_factory=_Reservoir)

    def add(self, record: Dict) -> None:
        predicted = float(record["predicted_seconds"])
        measured = float(record["measured_seconds"])
        self.n += 1
        self.predicted_sum += predicted
        self.measured_sum += measured
        self.queue_wait_sum += float(record.get("queue_wait_seconds") or 0.0)
        if record.get("compile") == COMPILE_COLD:
            self.cold_n += 1
        if self.kind is None and record.get("kind"):
            self.kind = str(record["kind"])
        self.measured.add(measured)

    @property
    def ratio(self) -> Optional[float]:
        """Aggregate measured/predicted — sums, not a mean of per-job
        ratios, so one mispredicted quick job cannot dominate."""
        if self.n < MIN_CALIBRATION_SAMPLES or self.predicted_sum <= 0:
            return None
        return self.measured_sum / self.predicted_sum

    def summary(self) -> Dict[str, object]:
        """JSON summary (fleet stats + the post-mortem report)."""
        return {
            "fingerprint": self.fingerprint,
            "kind": self.kind,
            "n": self.n,
            "cold_n": self.cold_n,
            "ratio": self.ratio,
            "predicted_mean_seconds": (
                self.predicted_sum / self.n if self.n else None
            ),
            "measured_mean_seconds": (
                self.measured_sum / self.n if self.n else None
            ),
            "queue_wait_mean_seconds": (
                self.queue_wait_sum / self.n if self.n else None
            ),
            "measured_seconds": {
                "p50": self.measured.quantile(0.50),
                "p95": self.measured.quantile(0.95),
                "p99": self.measured.quantile(0.99),
            },
        }


class CalibrationFold:
    """Order-insensitive in-memory fold of ledger records: per-geometry
    stats plus one overall aggregate (the fallback ratio for a geometry
    the fleet has never completed)."""

    def __init__(self) -> None:
        self.per_geometry: Dict[str, GeometryCalibration] = {}
        self.overall = GeometryCalibration(fingerprint="*")

    def add(self, record: Dict) -> bool:
        """Fold one parsed record; ``False`` (skipped) on junk — the
        torn-tail contract, shared with the disk reader."""
        if not isinstance(record, dict):
            return False
        # Non-done rows (a stolen job the survivor failed structurally,
        # a crashed run) exist for the post-mortem report's per-job
        # join; their wall clock measures the failure path, not the
        # geometry's cost, so the ratio fold skips them.
        if record.get("status") not in (None, "done"):
            return False
        try:
            predicted = float(record["predicted_seconds"])
            measured = float(record["measured_seconds"])
        except (KeyError, TypeError, ValueError):
            return False
        if not (predicted == predicted and measured == measured):
            return False
        if predicted < 0 or measured < 0:
            return False
        fingerprint = record.get("fingerprint")
        if not isinstance(fingerprint, str) or not fingerprint:
            fingerprint = "unknown"
        stats = self.per_geometry.get(fingerprint)
        if stats is None:
            stats = GeometryCalibration(fingerprint=fingerprint)
            self.per_geometry[fingerprint] = stats
        stats.add(record)
        self.overall.add(record)
        return True

    def ratio_for(self, fingerprint: Optional[str]) -> Optional[float]:
        """The learned ratio for one geometry; falls back to the overall
        fleet ratio, then ``None`` (caller treats as 1.0)."""
        if fingerprint is not None:
            stats = self.per_geometry.get(fingerprint)
            if stats is not None and stats.ratio is not None:
                return stats.ratio
        return self.overall.ratio

    def calibrated_estimate(self, prediction):
        """Stamp the calibration onto a fresh
        :class:`~spark_examples_tpu.obs.costmodel.CostPrediction`
        (mutates and returns it): ``calibrated_seconds`` = predicted ×
        the learned ratio for its geometry. No applicable ratio leaves
        the prediction unstamped — ``best_estimate_seconds`` then reads
        the raw model."""
        ratio = self.ratio_for(prediction.fingerprint)
        if ratio is not None and ratio > 0:
            stats = self.per_geometry.get(prediction.fingerprint or "")
            source = (
                stats
                if stats is not None and stats.ratio is not None
                else self.overall
            )
            prediction.calibration_ratio = ratio
            prediction.calibration_samples = source.n
            prediction.calibrated_seconds = (
                prediction.predicted_seconds * ratio
            )
        return prediction

    def summary(self) -> Dict[str, object]:
        return {
            "samples": self.overall.n,
            "ratio": self.overall.ratio,
            "predicted_mean_seconds": (
                self.overall.predicted_sum / self.overall.n
                if self.overall.n
                else None
            ),
            "measured_mean_seconds": (
                self.overall.measured_sum / self.overall.n
                if self.overall.n
                else None
            ),
            "geometries": {
                fp: stats.summary()
                for fp, stats in sorted(self.per_geometry.items())
            },
        }


def fold_calibration(path: str) -> CalibrationFold:
    """Fold the on-disk ledger (possibly written by N replicas, possibly
    torn at the tail, possibly absent) — the offline reader ``obs
    report`` and daemon startup/refresh share."""
    fold = CalibrationFold()
    try:
        f = open(path, "r", encoding="utf-8")
    except FileNotFoundError:
        return fold
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            fold.add(record)
    return fold


class CalibrationLedger:
    """The appender half plus a live fold. One per daemon; N replicas
    hold one each against the same file. ``record`` appends durably AND
    folds in-process (this replica's samples are visible immediately);
    ``refresh`` re-folds the file to merge peers' appends."""

    def __init__(self, run_dir: str):
        self.path = calibration_path(run_dir)
        # lock order: ledger lock is a leaf — nothing else is acquired
        # while holding it; the fsync'd append happens under it, exactly
        # like the geometry ledger's (utils/cache.py) append discipline.
        self._lock = threading.Lock()
        self._fd: Optional[int] = None
        self._fold = fold_calibration(self.path)

    def record(
        self,
        *,
        fingerprint: Optional[str],
        kind: str,
        job_class: str,
        predicted_seconds: float,
        measured_seconds: float,
        queue_wait_seconds: Optional[float],
        compile: str,
        job_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        unix: Optional[float] = None,
        status: str = "done",
    ) -> Dict[str, object]:
        """Durably append one settled job's (predicted, measured) pair;
        returns the record as written. ``status`` other than ``"done"``
        (e.g. ``"failed"`` for a stolen job the survivor fenced off)
        keeps the row out of the ratio fold but in the post-mortem
        report; ``queue_wait_seconds=None`` omits the key (the recorder
        of the wait may have died with a peer replica)."""
        doc: Dict[str, object] = {
            "fingerprint": fingerprint or "unknown",
            "kind": kind,
            "job_class": job_class,
            "predicted_seconds": float(predicted_seconds),
            "measured_seconds": float(measured_seconds),
            "compile": (
                COMPILE_WARM if compile == COMPILE_WARM else COMPILE_COLD
            ),
        }
        if queue_wait_seconds is not None:
            doc["queue_wait_seconds"] = float(queue_wait_seconds)
        if status != "done":
            doc["status"] = str(status)
        if job_id is not None:
            doc["id"] = job_id
        if trace_id is not None:
            doc["trace"] = trace_id
        if unix is not None:
            doc["unix"] = float(unix)
        line = (json.dumps(doc, sort_keys=True) + "\n").encode("utf-8")
        with self._lock:
            if self._fd is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._fd = os.open(
                    self.path,
                    os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                    0o644,
                )
            os.write(self._fd, line)
            os.fsync(self._fd)
            self._fold.add(doc)
        return doc

    def refresh(self) -> "CalibrationFold":
        """Re-fold the file from disk (merging peer replicas' appends)
        and swap it in; returns the fresh fold."""
        fold = fold_calibration(self.path)
        with self._lock:
            self._fold = fold
        return fold

    @property
    def fold(self) -> CalibrationFold:
        with self._lock:
            return self._fold

    def calibrated_estimate(self, prediction):
        return self.fold.calibrated_estimate(prediction)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


__all__ = [
    "CALIBRATION_BASENAME",
    "CalibrationFold",
    "CalibrationLedger",
    "GeometryCalibration",
    "MIN_CALIBRATION_SAMPLES",
    "RESERVOIR_CAPACITY",
    "calibration_path",
    "fold_calibration",
]

"""Structured telemetry: metrics registry, run spans, heartbeat, manifest.

The reference delegated all observability to the Spark web UI and log4j
(SURVEY.md §5); our earlier stand-ins were print-only strings scattered
across four modules — invisible to ``bench.py``, CI, and multi-host runs.
This package is the structured replacement:

- :mod:`metrics <spark_examples_tpu.obs.metrics>` — a thread-safe registry
  of named, labeled counters / gauges / histograms with JSON and
  Prometheus-text export. Every ad-hoc counter in the pipeline
  (``pipeline/stats.py``, ``sources/*`` client counters, the Gramian flush
  accounting) is now a view over this registry.
- :mod:`spans <spark_examples_tpu.obs.spans>` — hierarchical run spans
  (ingest → chunk-parse → dispatch → reduce-flush → eigh) with the honest
  device-sync semantics of ``StageTimes.stage(sync=)`` carried over.
- :mod:`heartbeat <spark_examples_tpu.obs.heartbeat>` — a background
  progress line for long runs (``--heartbeat-seconds``).
- :mod:`manifest <spark_examples_tpu.obs.manifest>` — the schema-versioned
  end-of-run machine-readable manifest (``--metrics-json``), consumed by
  ``bench.py`` and aggregated across processes under ``jax.distributed``.

Naming scheme (see DESIGN.md §9): ``<subsystem>_<what>[_<unit>]``;
counters end in ``_total``, durations in ``_seconds``. Subsystem prefixes:
``io_`` (dataset I/O stats), ``ingest_`` (parse/overlap/progress),
``prefetch_`` (the bounded queue), ``gramian_`` (accumulator flushes),
``client_`` (per-source request counters).
"""

from spark_examples_tpu.obs.metrics import MetricsRegistry
from spark_examples_tpu.obs.spans import SpanRecorder

__all__ = ["MetricsRegistry", "SpanRecorder"]

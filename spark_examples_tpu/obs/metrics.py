"""Thread-safe metrics registry: named, labeled counters / gauges / histograms.

One :class:`MetricsRegistry` per run (the driver owns it), so concurrent
runs and tests never cross-contaminate; components that run standalone
(``bench.py`` component benchmarks, the public API) create private
registries. All mutation is lock-protected per metric child — ingest worker
threads, the prefetch producer, and the driver thread all write
concurrently.

Two exports, one data model:

- :meth:`MetricsRegistry.as_dict` — the JSON form embedded in the run
  manifest (``obs/manifest.py``);
- :meth:`MetricsRegistry.prometheus_text` — the Prometheus text exposition
  format, for scraping a long-running job's state out of a heartbeat dump
  or a sidecar.

Registration is idempotent: asking for an existing name with the same type
and label names returns the existing family; a mismatch raises (two
subsystems silently sharing one name with different meanings is exactly the
ad-hoc-counter failure mode this registry replaces).
"""

from __future__ import annotations

import math
import re
import sys
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: Default histogram bucket upper bounds (seconds-oriented; +Inf implied).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
)

#: Wall-clock buckets for whole-job latencies (seconds; +Inf implied).
#: DEFAULT_BUCKETS tops out at 60 s — fine for flush/RPC timings, but a
#: whole-genome large-class serve job can run minutes, and every quantile
#: above the top bound collapses into +Inf (``histogram_quantile`` can
#: only answer "more than 60"). These extend to an hour so fleet P99s
#: stay interpolable across the full measured job-latency range.
WIDE_SECONDS_BUCKETS: Tuple[float, ...] = (
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0, 900.0, 3600.0,
)


class MetricError(ValueError):
    """Invalid metric registration or use (name/type/label mismatch)."""


#: Well-known gauge names the heartbeat samples (``obs/heartbeat.py``) —
#: ONE spelling and help string, shared by every producer, so a typo
#: cannot silently register a second family the heartbeat never reads.
INGEST_SITES_SCANNED = "ingest_sites_scanned"
INGEST_PARTITIONS_PLANNED = "ingest_partitions_planned"
INGEST_PARTITIONS_DONE = "ingest_partitions_done"
PREFETCH_QUEUE_DEPTH = "prefetch_queue_depth"
PREFETCH_QUEUE_OCCUPANCY = "prefetch_queue_occupancy"
GRAMIAN_INFLIGHT_DISPATCHES = "gramian_inflight_dispatches"
DEVICEGEN_DISPATCHES = "devicegen_dispatches"
DEVICEGEN_SITES_CAPACITY = "devicegen_sites_capacity"

#: Well-known ring-exchange telemetry (sharded Gramian paths). The bytes
#: counter is the number the bit-packed wire format cuts 8×; CI's
#: sharded-ring smoke asserts the packed/oracle ratio from run manifests.
GRAMIAN_RING_BYTES = "gramian_ring_bytes"
GRAMIAN_RING_FLUSH_SECONDS = "gramian_ring_flush_seconds"

#: Gramian exactness cross-validation pair (``graftcheck ranges``'s runtime
#: half, ``--check-ranges``): the measured max |accumulator entry| sampled
#: per flush next to the statically-projected bound the conversion trigger
#: maintains (``ops/contracts.py:flush_entry_increment`` accumulated over
#: flushes). The run manifest records both; the obs smoke asserts
#: measured <= proven — mirroring the hostmem RSS/bound pair.
GRAMIAN_ENTRY_MAX = "gramian_entry_max"
GRAMIAN_STATIC_ENTRY_BOUND = "gramian_static_entry_bound"

#: Registry-backed stats counter the heartbeat's per-shard progress reads
#: (registered by ``pipeline/stats.py:_STAT_METRICS``, spelled once here).
IO_PARTITIONS_TOTAL = "io_partitions_total"

#: Gramian crash-consistency telemetry (``pipeline/checkpoint.py:
#: GramianFeeder``, ``--gramian-checkpoint-dir``): how many atomic
#: accumulator snapshots this run published, and the ingest cursor (sites)
#: of the newest one — what a preemption would resume from.
GRAMIAN_CHECKPOINT_SAVES = "gramian_checkpoint_saves_total"
GRAMIAN_CHECKPOINT_SITES = "gramian_checkpoint_sites"

#: Transient-failure pressure: bounded-backoff retries issued by network
#: clients (``sources/rest.py``; registered via ``pipeline/stats.py`` so
#: the run manifest shows how hard the backend pushed back).
IO_RETRIES_TOTAL = "io_retries_total"

#: Self-healing serve loop (``serve/daemon.py``): times the watchdog
#: replaced a dead worker thread — every increment is one crash the daemon
#: survived instead of wedging.
SERVE_WORKER_RESTARTS = "serve_worker_restarts_total"

#: Warm-geometry compile-cache pair (``utils/cache.py``'s process-wide
#: ledger): how many runs hit an already-compiled analysis geometry vs
#: paid a cold compile. Function-backed (the ledger lives in utils.cache,
#: not the registry), sampled by the heartbeat, recorded in the manifest's
#: ``compile_cache`` block — the resident service's compile-once promise
#: is observable per scrape, not inferred from latency.
COMPILE_CACHE_GEOMETRY_HITS = "compile_cache_geometry_hits"
COMPILE_CACHE_GEOMETRY_MISSES = "compile_cache_geometry_misses"

#: Resident-service (``serve/``) liveness gauges the heartbeat samples:
#: admitted-but-unstarted jobs across both admission classes, the 0/1
#: in-flight flag of the single serial worker, and the lifetime count of
#: jobs that reached a terminal state.
SERVE_QUEUE_DEPTH = "serve_queue_depth"
SERVE_JOBS_INFLIGHT = "serve_jobs_inflight"
SERVE_JOBS_DONE = "serve_jobs_done"

#: Executor-slice topology gauges (``serve/daemon.py``): how many
#: independent slices partition the daemon's devices, and how many are
#: executing a job right now — the heartbeat's concurrency segment (a
#: busy large slice with idle small slices is the healthy mixed-traffic
#: picture; every slice busy is saturation).
SERVE_SLICES = "serve_slices"
SERVE_SLICES_BUSY = "serve_slices_busy"

#: Continuous-batching counters (``serve/queue.py:pop_batch``): dispatch
#: groups that coalesced more than one small job, and the total jobs that
#: rode them — the throughput the admission queue recovered from
#: fingerprint-compatible traffic.
SERVE_BATCHES = "serve_batches_total"
SERVE_BATCH_JOBS = "serve_batch_jobs_total"

#: Fused batch execution (``pipeline/fused.py``): groups that ran as ONE
#: stacked device program (a leading jobs axis over the Gramian update),
#: and the jobs that rode them. A group counted under SERVE_BATCHES but
#: not here fell back to serial back-to-back dispatch (ineligible mix or
#: stacked-HBM cap).
SERVE_FUSED_GROUPS = "serve_fused_groups_total"
SERVE_FUSED_JOBS = "serve_fused_jobs_total"

#: Jobs replayed from the on-disk job journal (``serve/journal.py``) at
#: daemon startup — each one an admission a previous incarnation
#: acknowledged and this one honored.
SERVE_JOURNAL_REPLAYED = "serve_journal_replayed_total"

#: Multi-replica serving (``serve/journal.py`` leases over the shared
#: journal): lease renewals this replica performed, expired-lease jobs it
#: stole from dead peers, and how many replicas are heartbeating against
#: the shared run dir right now (self included) — the capacity picture a
#: load balancer reads off any replica's scrape.
SERVE_LEASE_RENEWALS = "serve_lease_renewals_total"
SERVE_JOBS_STOLEN = "serve_jobs_stolen_total"
SERVE_REPLICAS_ALIVE = "serve_replicas_alive"

#: Fleet cost observatory (``obs/costmodel.py`` + ``obs/calibration.py``):
#: queue-wait and whole-job wall-clock histograms (wall labeled
#: ``kind``/``job_class``/``compile`` so warm and cold populations never
#: blur into one distribution), and the measured/predicted ratio of the
#: most recent completed job per kind — the live needle of the
#: calibration ledger's per-geometry fold.
SERVE_QUEUE_WAIT_SECONDS = "serve_queue_wait_seconds"
SERVE_JOB_WALL_SECONDS = "serve_job_wall_seconds"
COST_PREDICTION_RATIO = "cost_prediction_ratio"

#: Calibration-ledger fold summary gauges the heartbeat samples
#: (``obs/heartbeat.py`` cost segment): mean predicted and measured wall
#: seconds over the folded ledger and the sample count behind them.
COST_PREDICTED_MEAN_SECONDS = "cost_predicted_mean_seconds"
COST_MEASURED_MEAN_SECONDS = "cost_measured_mean_seconds"
COST_CALIBRATION_SAMPLES = "cost_calibration_samples"

#: Host-memory cross-validation pair (``graftcheck hostmem``'s runtime
#: half): the measured peak process RSS (function-backed — every read
#: samples the OS) next to the static bound from
#: ``parallel/mesh.py:host_peak_bytes``. The heartbeat samples the pair
#: per tick; the run manifest records both; CI asserts measured <= bound.
HOST_PEAK_RSS_BYTES = "host_peak_rss_bytes"
HOST_STATIC_BOUND_BYTES = "host_static_bound_bytes"

#: Per-site analysis progress (``analyses/``): sites a GRM/LD/assoc run
#: has tested so far, and — for pruning analyses — how many survived. The
#: manifest's ``analysis`` block snapshots the pair; the heartbeat samples
#: them like any ingest gauge, so a whole-genome LD prune shows live
#: kept/tested counts instead of hours of silence.
ANALYSIS_SITES_TESTED = "analysis_sites_tested"
ANALYSIS_SITES_KEPT = "analysis_sites_kept"

#: Prover-conformance pair: for each static prover with a runtime-measurable
#: subject, the MEASURED value next to the PROVEN bound, as one labeled
#: gauge family (``prover="hostmem" | "sched" | "ranges"``). The provers:
#: ``hostmem`` — peak process RSS vs ``parallel/mesh.py:host_peak_bytes``;
#: ``sched`` — per-flush-accounted ring bytes vs the schedule's static
#: projection (``graftcheck sched`` GI005/GS002 certify the same formula
#: device-free); ``ranges`` — max |Gramian accumulator entry| vs the
#: GR005-proven conversion-trigger projection (``--check-ranges``).
#: Registered by the driver's epilogue, embedded in the run manifest's
#: ``conformance`` block, mirrored into the serve registry per completed
#: job so ``GET /metrics`` exports the fleet's latest pair per prover —
#: the regression tripwire: measured must NEVER exceed proven.
PROVER_CONFORMANCE_MEASURED = "prover_conformance_measured"
PROVER_CONFORMANCE_PROVEN = "prover_conformance_proven"
CONFORMANCE_PROVERS = ("hostmem", "sched", "ranges")

_WELL_KNOWN_GAUGE_HELP = {
    INGEST_SITES_SCANNED: (
        "Candidate sites scanned so far (heartbeat progress)."
    ),
    INGEST_PARTITIONS_PLANNED: (
        "Shard windows this run will process (heartbeat ETA base)."
    ),
    INGEST_PARTITIONS_DONE: (
        "Shard windows the run has reached so far."
    ),
    PREFETCH_QUEUE_DEPTH: "Bound of the prefetch queue.",
    PREFETCH_QUEUE_OCCUPANCY: (
        "Parsed blocks currently waiting in the prefetch queue."
    ),
    GRAMIAN_INFLIGHT_DISPATCHES: (
        "Flushed device updates currently left in flight "
        "(the double-buffered feed depth)."
    ),
    DEVICEGEN_DISPATCHES: (
        "Fused generate+accumulate device dispatches issued."
    ),
    DEVICEGEN_SITES_CAPACITY: (
        "Site-grid capacity of every dispatch issued (padding included, "
        "summed over data slices) — the denominator of the dispatch "
        "padding-waste fraction against ingest_sites_scanned."
    ),
    GRAMIAN_ENTRY_MAX: (
        "Measured max |Gramian accumulator entry| across flushes "
        "(--check-ranges debug sampling; must stay <= "
        "gramian_static_entry_bound)."
    ),
    GRAMIAN_STATIC_ENTRY_BOUND: (
        "Statically-projected per-entry accumulator bound "
        "(ops/contracts.py:flush_entry_increment accumulated over flushes "
        "— the conversion trigger's own projection, proven conservative "
        "by graftcheck ranges GR005)."
    ),
    HOST_PEAK_RSS_BYTES: (
        "Peak resident set size of this process so far (OS-reported "
        "high-water mark, sampled at read time)."
    ),
    HOST_STATIC_BOUND_BYTES: (
        "Static host-memory bound of this configuration "
        "(parallel/mesh.py:host_peak_bytes); measured peak RSS must stay "
        "under it on bounded ingest paths."
    ),
    COMPILE_CACHE_GEOMETRY_HITS: (
        "Runs in this process that hit an already-compiled analysis "
        "geometry (utils/cache.py warm-geometry ledger)."
    ),
    COMPILE_CACHE_GEOMETRY_MISSES: (
        "Runs in this process that paid a cold compile for a fresh "
        "analysis geometry (utils/cache.py warm-geometry ledger)."
    ),
    SERVE_QUEUE_DEPTH: (
        "Admitted jobs waiting in the service's two-class admission "
        "queue (both classes)."
    ),
    SERVE_JOBS_INFLIGHT: (
        "Jobs the service's slice workers are executing right now "
        "(bounded by the executor-slice count)."
    ),
    SERVE_JOBS_DONE: (
        "Service jobs that reached a terminal state (done, failed, or "
        "cancelled) since the daemon started."
    ),
    SERVE_SLICES: (
        "Executor slices partitioning the daemon's devices "
        "(parallel/mesh.py:plan_executor_slices)."
    ),
    SERVE_SLICES_BUSY: (
        "Executor slices currently executing a job (each slice runs its "
        "dispatch group serially)."
    ),
    GRAMIAN_CHECKPOINT_SITES: (
        "Ingest cursor (rows of the deterministic stream) covered by the "
        "newest published Gramian checkpoint — what a preemption would "
        "resume from."
    ),
    ANALYSIS_SITES_TESTED: (
        "Sites this per-site analysis (analyses/: GRM, LD prune, assoc "
        "scan) has tested so far."
    ),
    ANALYSIS_SITES_KEPT: (
        "Sites the pruning analysis has kept so far (LD kept-mask "
        "cardinality; equals tested for non-pruning analyses)."
    ),
    SERVE_REPLICAS_ALIVE: (
        "Replica daemons currently heartbeating against this shared run "
        "dir, self included (serve/journal.py lease substrate)."
    ),
    COST_PREDICTED_MEAN_SECONDS: (
        "Mean predicted wall seconds over the folded calibration ledger "
        "(obs/calibration.py; the heartbeat's cost segment numerator)."
    ),
    COST_MEASURED_MEAN_SECONDS: (
        "Mean measured wall seconds over the folded calibration ledger "
        "(obs/calibration.py; pairs with cost_predicted_mean_seconds)."
    ),
    COST_CALIBRATION_SAMPLES: (
        "Completed (predicted, measured) job pairs folded into the "
        "calibration ledger so far — the n behind the learned ratios."
    ),
}

_WELL_KNOWN_COUNTER_HELP = {
    GRAMIAN_RING_BYTES: (
        "Total ICI bytes moved by ring-exchange ppermutes (sharded "
        "Gramian); the bit-packed wire format cuts this 8x vs unpacked "
        "uint8 tiles."
    ),
    GRAMIAN_CHECKPOINT_SAVES: (
        "Atomic Gramian accumulator snapshots published by this run "
        "(--gramian-checkpoint-dir)."
    ),
    IO_RETRIES_TOTAL: (
        "Transient-failure retries (bounded-backoff) issued by network "
        "clients — the manifest's transient-pressure signal."
    ),
    SERVE_WORKER_RESTARTS: (
        "Dead worker threads the serve watchdog replaced; each increment "
        "is one crash the daemon survived instead of wedging."
    ),
    SERVE_BATCHES: (
        "Dispatch groups that coalesced more than one compatible small "
        "job (continuous batching over the admission queue)."
    ),
    SERVE_BATCH_JOBS: (
        "Small jobs that rode a multi-job dispatch group (continuous "
        "batching over the admission queue)."
    ),
    SERVE_FUSED_GROUPS: (
        "Dispatch groups executed as ONE stacked device program "
        "(pipeline/fused.py) — one dispatch and one reduction per step "
        "for the whole group."
    ),
    SERVE_FUSED_JOBS: (
        "Jobs that rode a fused stacked device program instead of a "
        "serial back-to-back dispatch."
    ),
    SERVE_JOURNAL_REPLAYED: (
        "Accepted-but-unfinished jobs replayed from the job journal at "
        "daemon startup (serve/journal.py)."
    ),
    SERVE_LEASE_RENEWALS: (
        "Job-lease renewals this replica performed against the shared "
        "run dir (serve/journal.py lease substrate)."
    ),
    SERVE_JOBS_STOLEN: (
        "Jobs this replica reclaimed from a dead peer's expired lease "
        "(epoch-fenced work stealing over the shared journal)."
    ),
}


def well_known_gauge(registry: "MetricsRegistry", name: str):
    """Register (idempotently) one of the heartbeat's well-known gauges
    with its canonical help text."""
    return registry.gauge(name, _WELL_KNOWN_GAUGE_HELP[name])


def well_known_counter(registry: "MetricsRegistry", name: str):
    """Register (idempotently) a well-known counter with its canonical help
    text — one spelling shared by every producer (``ops/gramian.py``'s
    flush telemetry and the driver's device-ingest epilogue), the heartbeat,
    bench.py, and CI's manifest assertions."""
    return registry.counter(name, _WELL_KNOWN_COUNTER_HELP[name])


_CONFORMANCE_HELP = {
    PROVER_CONFORMANCE_MEASURED: (
        "Measured value of a static prover's runtime subject, by prover "
        "(hostmem: peak RSS bytes; sched: accounted ring bytes; ranges: "
        "max |Gramian entry|). Must stay <= prover_conformance_proven."
    ),
    PROVER_CONFORMANCE_PROVEN: (
        "Statically-proven bound of the same subject, by prover "
        "(hostmem: host_peak_bytes; sched: the schedule's ring-byte "
        "projection; ranges: the GR005-proven entry projection)."
    ),
}


def record_prover_conformance(
    registry: "MetricsRegistry",
    prover: str,
    measured: float,
    proven: Optional[float],
) -> None:
    """Register one prover's measured/proven pair as the labeled
    conformance gauges (idempotent; re-recording overwrites — the pair is
    a run-level snapshot, not an accumulator). ``proven=None`` records the
    measured side only — kept for provers whose bound is conditional
    (hostmem's never is: ``conf_host_peak_bytes`` is total, so its
    callers always pass a real bound)."""
    if prover not in CONFORMANCE_PROVERS:
        raise MetricError(
            f"unknown conformance prover {prover!r} "
            f"(one of {CONFORMANCE_PROVERS})"
        )
    registry.gauge(
        PROVER_CONFORMANCE_MEASURED,
        _CONFORMANCE_HELP[PROVER_CONFORMANCE_MEASURED],
        labelnames=("prover",),
    ).labels(prover=prover).set(float(measured))
    # proven=None SETS NaN rather than skipping: re-recording over an
    # earlier pair must never leave a stale proven bound behind (the
    # serve mirror is last-write-wins per prover — pairing one job's
    # measured with another job's proven would fabricate verdicts).
    registry.gauge(
        PROVER_CONFORMANCE_PROVEN,
        _CONFORMANCE_HELP[PROVER_CONFORMANCE_PROVEN],
        labelnames=("prover",),
    ).labels(prover=prover).set(
        float(proven) if proven is not None else float("nan")
    )


def conformance_block(registry: "MetricsRegistry") -> Optional[Dict]:
    """The run manifest's ``conformance`` block, read back from the
    labeled gauges: ``{prover: {measured, proven, ok} | null}`` per
    registered prover (``ok`` is null when no bound was provable), or
    ``None`` when no prover recorded a pair — manifests of runs without
    conformance telemetry are unchanged."""
    out: Dict[str, Optional[Dict]] = {}
    any_present = False
    for prover in CONFORMANCE_PROVERS:
        measured = registry.value(
            PROVER_CONFORMANCE_MEASURED, labels={"prover": prover}
        )
        if measured is None or measured != measured:
            out[prover] = None
            continue
        any_present = True
        proven = registry.value(
            PROVER_CONFORMANCE_PROVEN, labels={"prover": prover}
        )
        has_bound = proven is not None and proven == proven
        if has_bound:
            # The verdict compares the RAW floats; the displayed ints
            # (the validator's int contract) then round in the verdict's
            # direction — floor/ceil chosen so `measured <= proven` over
            # the INTS holds iff `ok` does. Consumers re-deriving the
            # comparison from the block (or from a re-recorded mirror of
            # it, serve/daemon.py:_mirror_conformance) can never see a
            # violated bound read as a pass, or the reverse.
            ok = bool(measured <= proven)
            if ok:
                measured_int = int(math.floor(measured))
                proven_int: Optional[int] = int(math.ceil(proven))
            else:
                measured_int = int(math.ceil(measured))
                proven_int = int(math.floor(proven))
        else:
            ok = None
            measured_int = int(round(measured))
            proven_int = None
        out[prover] = {
            "measured": measured_int,
            "proven": proven_int,
            "ok": ok,
        }
    return out if any_present else None


def read_host_peak_rss_bytes() -> Optional[int]:
    """OS-reported peak RSS of this process in BYTES, or ``None`` when the
    platform exposes neither ``getrusage`` nor ``/proc/self/status``.

    ``ru_maxrss`` is kibibytes on Linux and bytes on macOS (the one
    platform quirk this helper owns, so no caller re-derives it);
    ``VmHWM`` is the fallback for environments whose libc stubs rusage.
    """
    try:
        import resource

        rss = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        if rss > 0:
            return rss if sys.platform == "darwin" else rss * 1024
    except Exception:
        pass
    try:
        with open("/proc/self/status", "r", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) * 1024
    except Exception:
        pass
    return None


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise MetricError(f"invalid metric name {name!r}")
    return name


class _Child:
    """One (labels → value) series of a family."""

    def __init__(self, labels: Tuple[Tuple[str, str], ...]):
        self._labels = labels
        # lock order: leaf lock, taken last; no other lock is acquired
        # while holding it (mutations are single-value updates).
        self._lock = threading.Lock()

    @property
    def labels_dict(self) -> Dict[str, str]:
        return dict(self._labels)


class Counter(_Child):
    """Monotonic counter."""

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Child):
    """Settable value; optionally backed by a callable sampled at read
    time (queue occupancy, in-flight depth — state that lives elsewhere).

    The two modes are exclusive: ``set()`` detaches any function (the
    owner freezing a live gauge at teardown), while ``inc``/``dec`` on a
    function-backed gauge raise — the delta would be silently shadowed by
    the callable on every read, which is exactly the kind of quiet
    accounting loss this registry exists to prevent.
    """

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            if self._fn is not None:
                raise MetricError(
                    "gauge is function-backed; inc/dec would be shadowed "
                    "by the sampler (set() detaches it first)"
                )
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Sample ``fn`` on every read — the gauge tracks live state
        without the owner having to push updates."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return float("nan")


class Histogram(_Child):
    """Fixed-bucket histogram (cumulative counts, Prometheus-style)."""

    def __init__(self, labels, buckets: Sequence[float]):
        super().__init__(labels)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise MetricError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> Dict[str, object]:
        """Cumulative bucket counts keyed by upper bound, plus sum/count."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cumulative: Dict[str, int] = {}
        running = 0
        for bound, c in zip(self.buckets, counts[:-1]):
            running += c
            cumulative[_format_bound(bound)] = running
        cumulative["+Inf"] = running + counts[-1]
        return {"buckets": cumulative, "sum": total, "count": n}

    @property
    def value(self) -> Dict[str, object]:
        return self.snapshot()


def _format_bound(bound: float) -> str:
    if math.isinf(bound):
        return "+Inf"
    text = repr(bound)
    return text[:-2] if text.endswith(".0") else text


def _parse_bound(text: str) -> float:
    return float("inf") if text == "+Inf" else float(text)


def histogram_quantile(snapshot: Mapping, q: float) -> Optional[float]:
    """Estimate the q-quantile of a :meth:`Histogram.snapshot` (or any
    dict shaped like one: cumulative ``buckets`` keyed by upper-bound
    string, plus ``count``) by linear interpolation inside the target
    bucket — the Prometheus ``histogram_quantile`` estimator, applied to
    one snapshot instead of a rate.

    Contract (the edges tests pin):

    - empty histogram (``count == 0``) → ``None`` — "no data" must be
      distinguishable from "0 seconds";
    - ``q <= 0`` → the lower edge of the first populated bucket (0.0
      when that is the first bucket — observations have no recorded
      lower bound below their bucket floor);
    - ``q >= 1`` → the upper bound of the highest populated bucket;
    - mass landing in ``+Inf`` reports the highest FINITE bound — the
      estimator cannot see above the top bucket, and returning a finite
      floor ("at least this") beats returning infinity. Callers sizing
      buckets for real latencies want :data:`WIDE_SECONDS_BUCKETS`.
    """
    buckets = snapshot.get("buckets") or {}
    count = int(snapshot.get("count") or 0)
    if count <= 0 or not buckets:
        return None
    pairs = sorted(
        ((_parse_bound(k), int(v)) for k, v in buckets.items()),
        key=lambda kv: kv[0],
    )
    top_finite = max(
        (b for b, _ in pairs if not math.isinf(b)), default=0.0
    )
    rank = min(max(float(q), 0.0), 1.0) * count
    prev_bound = 0.0
    prev_cumulative = 0
    for bound, cumulative in pairs:
        if cumulative > prev_cumulative and rank <= cumulative:
            if rank <= prev_cumulative:
                return prev_bound
            if math.isinf(bound):
                return top_finite
            fraction = (rank - prev_cumulative) / (
                cumulative - prev_cumulative
            )
            return prev_bound + (bound - prev_bound) * fraction
        prev_cumulative = cumulative
        prev_bound = top_finite if math.isinf(bound) else bound
    return top_finite


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """A named metric with a fixed label-name set; children per label set."""

    def __init__(
        self,
        name: str,
        kind: str,
        help_text: str,
        labelnames: Tuple[str, ...],
        buckets: Optional[Sequence[float]] = None,
    ):
        self.name = _check_name(name)
        self.kind = kind
        self.help = help_text
        self.labelnames = labelnames
        self._buckets = buckets
        # lock order: family lock before any child lock (child creation);
        # never the reverse.
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Tuple[str, str], ...], _Child] = {}
        if not labelnames:
            self._default = self.labels()

    def labels(self, **labels: str) -> _Child:
        if set(labels) != set(self.labelnames):
            raise MetricError(
                f"{self.name}: expected labels {sorted(self.labelnames)}, "
                f"got {sorted(labels)}"
            )
        key = tuple((k, str(labels[k])) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "histogram":
                    child = Histogram(key, self._buckets or DEFAULT_BUCKETS)
                else:
                    child = _KINDS[self.kind](key)
                self._children[key] = child
            return child

    # Label-free convenience: the family IS its single child.
    def inc(self, amount: float = 1.0) -> None:
        self._require_default().inc(amount)  # type: ignore[union-attr]

    def dec(self, amount: float = 1.0) -> None:
        self._require_default().dec(amount)  # type: ignore[union-attr]

    def set(self, value: float) -> None:
        self._require_default().set(value)  # type: ignore[union-attr]

    def set_function(self, fn: Callable[[], float]) -> None:
        self._require_default().set_function(fn)  # type: ignore[union-attr]

    def observe(self, value: float) -> None:
        self._require_default().observe(value)  # type: ignore[union-attr]

    @property
    def value(self):
        return self._require_default().value

    def _require_default(self) -> _Child:
        if self.labelnames:
            raise MetricError(
                f"{self.name} is labeled {self.labelnames}; use .labels(...)"
            )
        return self._default

    def children(self) -> List[_Child]:
        with self._lock:
            return list(self._children.values())


class MetricsRegistry:
    """The registry: one per run (or per standalone component)."""

    def __init__(self) -> None:
        # lock order: registry lock before family lock; never the reverse.
        # This is the one real ordering edge in the shipped tree
        # (registry -> family, via family construction in _register) and
        # `graftcheck lockgraph` verifies the graph stays acyclic.
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # --------------------------------------------------------- registration

    def _register(
        self,
        name: str,
        kind: str,
        help_text: str,
        labelnames: Sequence[str],
        buckets: Optional[Sequence[float]] = None,
    ) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            family = self._families.get(name)
            if family is not None:
                if family.kind != kind or family.labelnames != labelnames:
                    raise MetricError(
                        f"metric {name!r} already registered as "
                        f"{family.kind}{family.labelnames}; requested "
                        f"{kind}{labelnames}"
                    )
                return family
            family = _Family(name, kind, help_text, labelnames, buckets)
            self._families[name] = family
            return family

    def counter(
        self, name: str, help_text: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._register(name, "counter", help_text, labelnames)

    def gauge(
        self, name: str, help_text: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._register(name, "gauge", help_text, labelnames)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> _Family:
        return self._register(name, "histogram", help_text, labelnames, buckets)

    # --------------------------------------------------------------- access

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def value(
        self, name: str, labels: Optional[Mapping[str, str]] = None, default=None
    ):
        """Convenience read (manifest/heartbeat/tests): the value of one
        series, or ``default`` when the metric or label set is absent."""
        family = self.get(name)
        if family is None:
            return default
        if not family.labelnames:
            return family.value
        want = {k: str(v) for k, v in (labels or {}).items()}
        for child in family.children():
            if child.labels_dict == want:
                return child.value
        return default

    # -------------------------------------------------------------- exports

    def as_dict(self) -> Dict[str, Dict]:
        """JSON-safe snapshot: ``{name: {type, help, values: [...]}}`` with
        one entry per label set (``value`` for counters/gauges; cumulative
        ``buckets``/``sum``/``count`` for histograms)."""
        out: Dict[str, Dict] = {}
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for family in families:
            values = []
            for child in family.children():
                entry: Dict[str, object] = {"labels": child.labels_dict}
                if family.kind == "histogram":
                    entry.update(child.snapshot())  # type: ignore[union-attr]
                else:
                    value = child.value  # type: ignore[union-attr]
                    entry["value"] = None if _is_nan(value) else value
                values.append(entry)
            out[family.name] = {
                "type": family.kind,
                "help": family.help,
                "values": values,
            }
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for family in families:
            if family.help:
                lines.append(
                    f"# HELP {family.name} {escape_help_text(family.help)}"
                )
            lines.append(f"# TYPE {family.name} {family.kind}")
            for child in family.children():
                label_text = _label_text(child.labels_dict)
                if family.kind == "histogram":
                    snap = child.snapshot()  # type: ignore[union-attr]
                    for bound, count in snap["buckets"].items():
                        le = _label_text({**child.labels_dict, "le": bound})
                        lines.append(f"{family.name}_bucket{le} {count}")
                    lines.append(
                        f"{family.name}_sum{label_text} {_num(snap['sum'])}"
                    )
                    lines.append(
                        f"{family.name}_count{label_text} {snap['count']}"
                    )
                else:
                    value = child.value  # type: ignore[union-attr]
                    lines.append(f"{family.name}{label_text} {_num(value)}")
        return "\n".join(lines) + "\n"


def _is_nan(value) -> bool:
    return isinstance(value, float) and math.isnan(value)


def _num(value: float) -> str:
    if _is_nan(value):
        return "NaN"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value)


def _label_text(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + body + "}"


def escape_label_value(value: str) -> str:
    """Label-value escaping per the text exposition format (v0.0.4):
    backslash FIRST (the escape character itself, so the later
    replacements cannot double-escape their own output), then the
    double-quote delimiter, then newline — the three characters the
    format names."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help_text(value: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    newline only (a ``#`` or quote is legal inside help text, but a raw
    newline would terminate the comment mid-help and turn the remainder
    into an unparseable exposition line)."""
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricError",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "WIDE_SECONDS_BUCKETS",
    "histogram_quantile",
    "SERVE_QUEUE_WAIT_SECONDS",
    "SERVE_JOB_WALL_SECONDS",
    "COST_PREDICTION_RATIO",
    "COST_PREDICTED_MEAN_SECONDS",
    "COST_MEASURED_MEAN_SECONDS",
    "COST_CALIBRATION_SAMPLES",
    "INGEST_SITES_SCANNED",
    "INGEST_PARTITIONS_PLANNED",
    "INGEST_PARTITIONS_DONE",
    "PREFETCH_QUEUE_DEPTH",
    "PREFETCH_QUEUE_OCCUPANCY",
    "GRAMIAN_INFLIGHT_DISPATCHES",
    "GRAMIAN_RING_BYTES",
    "GRAMIAN_RING_FLUSH_SECONDS",
    "GRAMIAN_ENTRY_MAX",
    "GRAMIAN_STATIC_ENTRY_BOUND",
    "GRAMIAN_CHECKPOINT_SAVES",
    "GRAMIAN_CHECKPOINT_SITES",
    "IO_RETRIES_TOTAL",
    "SERVE_WORKER_RESTARTS",
    "DEVICEGEN_DISPATCHES",
    "DEVICEGEN_SITES_CAPACITY",
    "IO_PARTITIONS_TOTAL",
    "COMPILE_CACHE_GEOMETRY_HITS",
    "COMPILE_CACHE_GEOMETRY_MISSES",
    "SERVE_QUEUE_DEPTH",
    "SERVE_JOBS_INFLIGHT",
    "SERVE_JOBS_DONE",
    "SERVE_SLICES",
    "SERVE_SLICES_BUSY",
    "SERVE_BATCHES",
    "SERVE_BATCH_JOBS",
    "SERVE_FUSED_GROUPS",
    "SERVE_FUSED_JOBS",
    "SERVE_JOURNAL_REPLAYED",
    "SERVE_LEASE_RENEWALS",
    "SERVE_JOBS_STOLEN",
    "SERVE_REPLICAS_ALIVE",
    "HOST_PEAK_RSS_BYTES",
    "HOST_STATIC_BOUND_BYTES",
    "PROVER_CONFORMANCE_MEASURED",
    "PROVER_CONFORMANCE_PROVEN",
    "CONFORMANCE_PROVERS",
    "conformance_block",
    "escape_help_text",
    "escape_label_value",
    "read_host_peak_rss_bytes",
    "record_prover_conformance",
    "well_known_gauge",
    "well_known_counter",
]

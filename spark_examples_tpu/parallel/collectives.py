"""Named-axis collective wrappers.

Every Spark shuffle/broadcast/reduce in the reference's call stacks
(SURVEY.md §3) maps onto one of these XLA collectives over ICI/DCN:

- ``reduceByKey`` partial-Gramian merge (``VariantsPca.scala:230``) → ``psum``
- ``sc.broadcast`` (``VariantsPca.scala:195,249``)            → replication
  (jit-constant or replicated sharding; no wrapper needed)
- ``collect`` to driver (``VariantsPca.scala:246``)           → device_get
  after an on-device reduction
- streaming pair-emission shuffle (``VariantsPca.scala:302-319``) →
  ``ppermute`` ring / ``psum_scatter`` tiles

These are thin on purpose: inside ``shard_map`` the named-axis primitives are
already the right API; wrapping keeps axis names consistent and gives the
runtime layer a single import surface.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS


def psum(x, axis_name: str = DATA_AXIS):
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str = DATA_AXIS):
    return lax.pmean(x, axis_name)

def psum_scatter(x, axis_name: str = SAMPLES_AXIS, *, scatter_dimension: int = 0,
                 tiled: bool = True):
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


def all_gather(x, axis_name: str = SAMPLES_AXIS, *, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all(x, axis_name: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def ring_permute(x, axis_name: str = SAMPLES_AXIS, shift: int = 1,
                 axis_size: Optional[int] = None):
    """Send ``x`` one step around the ring: device i receives from i+shift."""
    n = axis_size if axis_size is not None else lax.axis_size(axis_name)
    perm = [((i + shift) % n, i) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


__all__ = [
    "psum",
    "pmean",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "ring_permute",
    "axis_index",
]

"""Device mesh construction and multi-host initialization.

This module replaces the one layer the reference borrowed wholesale: Spark's
distributed runtime (shuffle/broadcast/accumulators over TCP, SURVEY.md §2.3).
The TPU equivalent is a named-axis device mesh with XLA collectives over ICI
(intra-slice) and DCN (cross-host):

- ``data`` axis — the coordinate/variant dimension: genotype blocks from
  different contig windows land on different devices, per-device partial
  Gramians are summed once at finalize (the ``reduceByKey`` shuffle at
  ``VariantsPca.scala:230`` becomes a single ``psum``).
- ``samples`` axis — the cohort dimension: for cohorts too large for a
  replicated N×N similarity matrix (the reference's ~50K-samples/20GB
  guidance, ``VariantsPca.scala:216-217``), the Gramian is sharded by sample
  row-tiles across this axis.

The reference's ``--num-reduce-partitions`` ("set it to a number greater than
the number of cores", ``GenomicsConf.scala:35-38``) maps onto the data-axis
size, per the BASELINE.json north star.

Every Spark shuffle/broadcast/reduce in the reference's call stacks
(SURVEY.md §3) maps onto an XLA collective over this mesh, used directly by
the ops layer inside ``shard_map`` (named-axis primitives are already the
right API — no wrapper layer):

- ``reduceByKey`` partial-Gramian merge (``VariantsPca.scala:230``) →
  ``psum`` over ``data`` (``ops/gramian.py``: finalize reduction);
- ``sc.broadcast`` (``VariantsPca.scala:195,249``) → replication
  (jit constants / replicated shardings);
- ``collect`` to driver (``VariantsPca.scala:246``) → one ``device_get``
  after on-device reduction (``pipeline/pca_driver.py:compute_pca``);
- streaming pair-emission shuffle (``VariantsPca.scala:302-319``) →
  ``ppermute`` ring exchange of sample-column tiles
  (``ops/gramian.py:_ring_tiles``);
- row-sums collect + re-broadcast for centering (``VariantsPca.scala:
  246-249``) → ``psum`` of column sums (``ops/centering.py:
  gower_center_sharded``);
- driver-side eigendecomposition (``VariantsPca.scala:264-266``) →
  ``all_gather`` of the skinny subspace iterate
  (``ops/pca.py:principal_components_subspace_sharded``).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SAMPLES_AXIS = "samples"
#: Outer axis of the hierarchical (two-level) reduction mesh: the samples
#: axis factored host-major into ``hosts x samples``, so the inner ring's
#: ``ppermute`` neighbors are intra-host (ICI) BY CONSTRUCTION and only the
#: outer ring crosses hosts (DCN). See :func:`hierarchical_mesh`.
HOST_AXIS = "hosts"

PLATFORM_ENV = "SPARK_EXAMPLES_TPU_PLATFORM"

#: Test/rehearsal override for the hierarchical schedule's host factor
#: (``resolve_hier_hosts``): lets a single-process run with virtual CPU
#: devices exercise a REAL two-level schedule (e.g. 2 "hosts" x 2 devices
#: on 4 virtual devices — the ci.sh hier smoke), the same trick
#: ``SPARK_EXAMPLES_TPU_PLATFORM`` plays for the multihost rehearsal.
HIER_HOSTS_ENV = "SPARK_EXAMPLES_TPU_HIER_HOSTS"

#: Genotypes per byte on the packed ring wire (np.packbits bit order). The
#: pack-width invariant follows from it: every device's local column width
#: must be a whole number of bytes, i.e. a multiple of this.
RING_PACK_MULTIPLE = 8


def padded_cohort(num_columns: int, samples_parallel: int, pack: bool = True) -> int:
    """Column count after cohort padding for the sharded ring Gramian.

    The cohort pads up to a multiple of the ``samples`` axis so every device
    owns an equal column tile; with the bit-packed ring wire format the tile
    additionally pads to a multiple of ``RING_PACK_MULTIPLE`` columns per
    device (a packed tile is a whole number of bytes, and a byte boundary
    must coincide with every shard boundary so each device's shard of the
    host-packed block is exactly its own columns). Pad columns are all-zero
    and contribute nothing to XᵀX; finalize trims them. ONE rule, shared by
    ``ops/gramian.py``, ``ops/devicegen.py`` and the device-free plan
    validator (``check/plan.py``) — the geometry the validator accepts is the
    geometry the accumulators build.
    """
    multiple = int(samples_parallel) * (RING_PACK_MULTIPLE if pack else 1)
    return -(-int(num_columns) // multiple) * multiple


def ring_traffic_bytes(
    rows: int, samples_parallel: int, n_local: int, packed: bool
) -> int:
    """Total ICI bytes one ring pass moves for ``rows`` variant rows.

    Each of the ``samples_parallel`` devices sends its ``(rows, width)``
    column tile ``samples_parallel - 1`` times around the ring; ``width`` is
    ``n_local`` bytes unpacked or ``n_local / 8`` packed (``n_local % 8 == 0``
    under the pack-width invariant — :func:`padded_cohort`). ``rows`` summed
    over data-parallel slices gives the whole-mesh total (each slice runs its
    own ring). The one audited formula behind the ``gramian_ring_bytes``
    telemetry (``obs/metrics.py``) and the plan validator's traffic facts;
    ``graftcheck ir`` (``check/ir.py``) cross-validates it against the
    bytes the traced kernel jaxprs actually move (ppermute operand bytes x
    scan trip counts x devices) and fails CI on any divergence (GI005), so
    a wire-format or ring-schedule change can never silently decouple the
    reported traffic from the real traffic.
    """
    width = (
        int(n_local) // RING_PACK_MULTIPLE if packed else int(n_local)
    )
    return int(rows) * int(samples_parallel) * (int(samples_parallel) - 1) * width


# --------------------------------------------------------------------------
# Topology & the hierarchical (two-level) reduction schedule.
# --------------------------------------------------------------------------

#: Default per-link bandwidths for the device-free schedule simulator
#: (``check/sched.py``). ICI: one v5e ring link sustains ~100 GB/s/chip
#: bidirectional (the packed ring moves one tile per step per link); DCN:
#: a v5e host NIC is ~25 GB/s aggregate and is SHARED by the host's chips.
#: Deliberately round, clearly-labeled planning numbers — the simulator's
#: job is comparing schedules and proving budgets, not cycle accuracy; a
#: ~2x bandwidth error never flips the flat-vs-hier ordering the GS rules
#: enforce (the byte SPLIT is exact, only seconds scale).
DEFAULT_ICI_BYTES_PER_S = 100 * 10**9
DEFAULT_DCN_BYTES_PER_S = 25 * 10**9


@dataclass(frozen=True)
class Topology:
    """A pod-shaped device fleet the schedule prover plans against:
    ``hosts`` machines x ``devices_per_host`` chips, intra-host links at
    ``ici_bytes_per_s`` per chip, one shared ``dcn_bytes_per_s`` NIC per
    host. Entirely declarative — a topology is proven against BEFORE the
    pod exists (``graftcheck sched --topology 32,8``), exactly like
    ``--plan-devices`` declares a device count the validator never
    queries."""

    hosts: int
    devices_per_host: int
    ici_bytes_per_s: int = DEFAULT_ICI_BYTES_PER_S
    dcn_bytes_per_s: int = DEFAULT_DCN_BYTES_PER_S

    def __post_init__(self) -> None:
        if self.hosts < 1 or self.devices_per_host < 1:
            raise ValueError(
                f"topology needs hosts >= 1 and devices_per_host >= 1, got "
                f"{self.hosts}x{self.devices_per_host}"
            )
        if self.ici_bytes_per_s <= 0 or self.dcn_bytes_per_s <= 0:
            raise ValueError("topology link bandwidths must be positive")

    @property
    def devices(self) -> int:
        return self.hosts * self.devices_per_host

    def describe(self) -> str:
        return f"{self.hosts}x{self.devices_per_host}"


def parse_topology(spec: str) -> Topology:
    """Parse the ``--topology`` flag: ``'hosts,devices_per_host'``
    (e.g. ``'32,8'`` for a v5e-256-class pod)."""
    parts = [p for p in spec.split(",") if p.strip()]
    if len(parts) != 2:
        raise ValueError(
            f"--topology expects 'hosts,devices_per_host', got {spec!r}"
        )
    try:
        hosts, per_host = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"--topology expects integer 'hosts,devices_per_host', got "
            f"{spec!r}"
        ) from None
    return Topology(hosts, per_host)


class LevelTraffic(NamedTuple):
    """Per-link-class bytes of one reduction schedule (whole mesh, one
    pass over ``rows``). ``ici_bytes`` ride intra-host links; ``dcn_bytes``
    ride the inter-host network. The split is the schedule's PROVABLE
    placement: bytes the schedule structure pins to a link class."""

    ici_bytes: int
    dcn_bytes: int

    @property
    def total(self) -> int:
        return self.ici_bytes + self.dcn_bytes


def hierarchical_traffic_bytes(
    rows: int,
    hosts: int,
    devices_per_host: int,
    n_local: int,
    packed: bool,
) -> LevelTraffic:
    """Per-level bytes of the two-level schedule — the sibling of
    :func:`ring_traffic_bytes`, split by link class.

    Per device and flush of ``rows`` rows: the inner packed ring sends the
    currently-held tile ``devices_per_host - 1`` times per outer step over
    ICI (``hosts`` outer steps, the seed included), and the outer ring
    sends it ``hosts - 1`` times over DCN — each host's columns cross DCN
    to every other host exactly ONCE, the information-theoretic floor for
    an all-to-all tile exchange. Total bytes equal the flat ring's
    (``S x (S-1)`` sends of the same tile, ``S = hosts x
    devices_per_host``): the hierarchical schedule moves the SAME bytes,
    it just proves where they ride. ``graftcheck sched`` (GS002)
    cross-validates both numbers against the bytes the traced kernel
    jaxprs actually move, per axis."""
    h, d = int(hosts), int(devices_per_host)
    width = int(n_local) // RING_PACK_MULTIPLE if packed else int(n_local)
    per_send = int(rows) * width
    devices = h * d
    return LevelTraffic(
        ici_bytes=per_send * devices * h * (d - 1),
        dcn_bytes=per_send * devices * (h - 1),
    )


def flat_traffic_split(
    rows: int, topology: Topology, n_local: int, packed: bool
) -> LevelTraffic:
    """The flat ring's provable per-level split on ``topology``.

    A flat ``ppermute`` over ONE mesh axis carries no host-boundary
    structure: which of its ``S - 1`` lockstep hops cross hosts is a
    property of the runtime device assignment, not of the schedule — so on
    a multi-host topology NO byte can be proven intra-host, and the sound
    bound attributes the whole circulation to the slow link. That
    unprovability is exactly what GS001 flags (and the hierarchical
    schedule fixes by construction: its inner axis is intra-host by the
    host-major mesh factorization). On one host everything is ICI."""
    total = ring_traffic_bytes(
        rows, topology.devices, n_local, packed
    )
    if topology.hosts == 1:
        return LevelTraffic(ici_bytes=total, dcn_bytes=0)
    return LevelTraffic(ici_bytes=0, dcn_bytes=total)


def resolve_reduce_schedule(spec: str, hosts: int) -> str:
    """``--reduce-schedule`` -> the schedule the run builds: ``flat`` (one
    ring over the whole samples axis), ``hier`` (packed intra-host ring
    over ICI + inter-host ring over DCN), or ``auto`` = ``hier`` iff the
    samples axis spans more than one host (single-host rings have no slow
    link to avoid — the flat ring IS the hierarchical schedule at
    hosts=1). ONE resolution rule, shared by the accumulator, the plan
    validator, and ``graftcheck sched``."""
    if spec not in ("auto", "flat", "hier"):
        raise ValueError(
            f"--reduce-schedule must be one of auto/flat/hier, got {spec!r}"
        )
    if spec == "auto":
        return "hier" if int(hosts) > 1 else "flat"
    return spec


def resolve_hier_hosts(
    samples_parallel: int, explicit: Optional[int] = None
) -> int:
    """The host factor of the hierarchical mesh factorization: explicit
    argument, else the :data:`HIER_HOSTS_ENV` rehearsal override, else the
    real process count. Must divide the samples axis (each host contributes
    an equal slice of the ring — the host-major factorization's invariant);
    a non-dividing factor fails loudly instead of silently skewing the
    schedule."""
    if explicit is None:
        env = os.environ.get(HIER_HOSTS_ENV)
        if env:
            explicit = int(env)
    hosts = int(explicit) if explicit is not None else jax.process_count()
    hosts = max(1, hosts)
    if int(samples_parallel) % hosts:
        raise ValueError(
            f"hierarchical schedule needs the host factor ({hosts}) to "
            f"divide the samples axis ({samples_parallel}); choose a mesh "
            "whose samples axis is a multiple of the host count"
        )
    return hosts


def hierarchical_mesh(mesh: Mesh, hosts: int) -> Mesh:
    """Factor a ``data x samples`` run mesh into the host-major
    ``data x hosts x samples`` hierarchical mesh (same devices, same
    order). The samples axis is the FAST axis of every run mesh
    (:func:`make_mesh` reshapes device-id order, which is process-major),
    so consecutive samples-axis slots are co-hosted and the reshape's
    outer factor groups whole hosts — the inner ring's neighbors stay
    intra-host by construction, which is the property the schedule prover
    certifies (``check/sched.py``)."""
    if SAMPLES_AXIS not in mesh.shape:
        raise ValueError(f"mesh must have a {SAMPLES_AXIS!r} axis")
    samples = mesh.shape[SAMPLES_AXIS]
    hosts = int(hosts)
    if samples % hosts:
        raise ValueError(
            f"host factor {hosts} does not divide samples axis {samples}"
        )
    data = mesh.shape.get(DATA_AXIS, 1)
    grid = np.asarray(mesh.devices).reshape(
        data, hosts, samples // hosts
    )
    return Mesh(grid, (DATA_AXIS, HOST_AXIS, SAMPLES_AXIS))


#: Fixed host-RSS overhead of the process itself — interpreter, jax/jaxlib
#: runtime, compiled executables, parser library — the constant term of
#: :func:`host_peak_bytes`. Deliberately generous: a CPU-backend process
#: idles around 0.3-0.6 GiB, and the TPU runtime maps a further ~2 GiB of
#: host memory at init (measured on the v5e-8 smoke). The formula's job
#: is to bound the DATA-DEPENDENT staging terms; an O(file) regression on
#: any real cohort dwarfs this constant long before the constant's slack
#: matters. Measured against reality on every build (ci.sh: manifest
#: ``host_memory.peak_rss_bytes`` <= the static bound).
HOST_RUNTIME_BASELINE_BYTES = 4 << 30


def host_peak_bytes(
    num_samples: int,
    block_size: int,
    data_axis: int = 1,
    ingest_workers: int = 0,
    chunk_bytes: int = 0,
    prefetch_depth: int = 0,
    pipeline_depth: int = 0,
    host_accumulator: bool = False,
    grm_finalize: bool = False,
    ld_window_sites: int = 0,
    num_hosts: int = 1,
    wire_table_bytes: int = 0,
    merge_join_bytes: int = 0,
    baseline_bytes: int = HOST_RUNTIME_BASELINE_BYTES,
) -> int:
    """Closed-form peak host-memory bound of one bounded-ingest run — the
    host-RAM sibling of :func:`ring_traffic_bytes`, and the ONE formula
    behind ``graftcheck plan --host-mem-budget``, the driver's
    ``host_static_bound_bytes`` gauge, and the manifest's ``host_memory``
    block (``check/hostmem.py:conf_host_peak_bytes`` resolves a parsed
    configuration into these arguments, so no caller re-derives them).

    Term by term (derivation in DESIGN.md §8.6):

    - **parse window** — ``(ingest_workers + 2) * 2 * chunk_bytes``: the
      order-preserving pool (``sources/files.py:_ordered_pool_map``) holds
      at most ``workers + 2`` chunks in flight, each present as raw text
      AND as its parsed arrays (has-variation bytes <= text bytes: one
      int8 per genotype vs >= 2 text chars per GT column, plus
      positions/ends/AF at ~20 bytes/row against ~60+ text bytes/row).
    - **prefetch queue** — ``prefetch_depth`` parsed blocks of
      ``block_size * num_samples`` uint8 waiting for the device feeder
      (``pipeline/datasets.py:PrefetchIterator``).
    - **accumulator staging** — the ``(data_axis * block_size,
      num_samples)`` uint8 staging buffer plus one flush copy (packed
      ``ceil(N/8)`` or the full-width counts copy — bound with the full
      width so count-valued joins stay inside the bound).
    - **flush in-flight** — ``pipeline_depth`` flush copies pinned on host
      while their transfers overlap compute (``ops/gramian.py``).
    - **host accumulator** — the ``--pca-backend host`` oracle's int64
      N x N matrix (+ its f64 centering copy), zero on the device path.
    - **GRM finalize** — ``21 * N * N``: the kinship close-out
      (``analyses/grm.py:grm_finalize`` + its summary) holds the fetched
      f32 Gramian (4 N²), EITHER the int64 working copy OR the summary's
      off-diagonal float64 extraction (8 N² — they never overlap), the
      float64 kinship itself (8 N²), and the off-diagonal bool mask
      (1 N²) simultaneously on host; zero for every other analysis.
    - **LD window** — ``56 * W² + W * N``: each flush fetches the W×W
      int32 co-carrier matrix and closes r² on host
      (``ops/ld.py:r2_from_counts`` holds up to seven 8-byte W×W working
      matrices — the int64 copy, cov, the variance outer product, the
      squared numerator and its cast temp, the r² result — next to the
      fetched int32 stats; 56 W² bounds the lot) plus the (W, N) uint8
      window buffer; zero when the run has no LD window.
    - **pod merge** — ``(num_hosts + 1) * 8 * N²`` when ``num_hosts > 1``:
      host-sharded ingest closes out by all-gathering every process's
      dense N×N partial Gramian onto each host and summing them exactly
      (``pipeline/pca_driver.py:_merge_host_partials``) — the gathered
      stack (``num_hosts`` partials) plus the 8-byte exact-sum working
      copy sit on host simultaneously. This is a PER-HOST bound: each
      process pays it locally, so the pod-wide peak is ``num_hosts``
      times this formula while each host stays within it. Zero for
      single-process runs.
    - **wire table** — ``wire_table_bytes``: the resolved residency of
      wire-mode ingest tables (spool index + decoded records + stream
      windows) or the packed columns' build/hand-off co-residency; the
      caller (``check/hostmem.py:conf_host_peak_bytes``) derives it from
      the bytes on disk via ``sources/stream.py:wire_rows_bound`` so the
      formula stays TOTAL across JSONL/SAM/REST/checkpoint-resume inputs.
    - **merge join** — ``merge_join_bytes``: the k-way streaming join's
      tracked-group working set, ``n_sets x 64 x record_bytes``
      (``sources/stream.py:merge_join`` holds at most the records of the
      current group key per stream; 64 is the accounted per-stream group
      ceiling its ``MergeJoinStats.peak_tracked`` gauge is asserted
      against). Zero for single-set runs.
    - **baseline** — :data:`HOST_RUNTIME_BASELINE_BYTES`.
    """
    n = int(num_samples)
    block_bytes = int(block_size) * n
    staging = int(data_axis) * block_bytes
    parse_window = (int(ingest_workers) + 2) * 2 * int(chunk_bytes)
    prefetch = int(prefetch_depth) * block_bytes
    flush_copies = (1 + int(pipeline_depth)) * staging
    host_matrix = 2 * n * n * 8 if host_accumulator else 0
    grm_term = 21 * n * n if grm_finalize else 0
    w = int(ld_window_sites)
    ld_term = 56 * w * w + w * n if w > 0 else 0
    hosts = int(num_hosts)
    merge_term = (hosts + 1) * 8 * n * n if hosts > 1 else 0
    return int(
        baseline_bytes
        + parse_window
        + prefetch
        + staging
        + flush_copies
        + host_matrix
        + grm_term
        + ld_term
        + merge_term
        + int(wire_table_bytes)
        + int(merge_join_bytes)
    )


def apply_platform_override() -> Optional[str]:
    """Honor ``SPARK_EXAMPLES_TPU_PLATFORM`` (e.g. ``cpu``) before any
    backend client exists.

    Images that pre-register an accelerator PJRT plugin from a
    ``sitecustomize`` hook pin the platform at interpreter start, so the
    standard ``JAX_PLATFORMS`` environment variable set at process launch is
    silently overridden; ``jax.config`` still wins if applied before the
    first client creation. This is how the multi-host harness
    (``parallel/multihost.py``) runs its children on a virtual CPU fleet on
    a single-TPU host."""
    platform = os.environ.get(PLATFORM_ENV)
    if platform:
        jax.config.update("jax_platforms", platform)
    return platform or None


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    initialization_timeout: Optional[float] = None,
) -> None:
    """Initialize multi-host JAX (``jax.distributed``) when configured.

    A no-op for single-process runs. Cross-host arguments may come from flags
    or the standard cluster environment variables JAX already understands;
    this wrapper only exists so the driver has one seam for it (the analog of
    ``conf.newSparkContext``, ``GenomicsConf.scala:50-57``).
    """
    given = (coordinator_address, num_processes, process_id)
    if all(v is None for v in given):
        return
    # The CPU backend runs cross-process collectives only through an
    # explicit collectives implementation; without this the first
    # multi-process dispatch dies with "Multiprocess computations aren't
    # implemented on the CPU backend". TPU/GPU ignore the flag, and it must
    # land before the backend client exists — i.e. here, alongside the
    # rest of distributed init. Best-effort: ancient jaxlibs without the
    # flag keep their previous behavior.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    if coordinator_address is None or num_processes is None:
        # A partially-specified cluster launch must not silently fall back
        # to a single-process run over 1/N of the fleet.
        raise ValueError(
            "multi-host init needs --coordinator-address and --num-processes "
            f"(got coordinator_address={coordinator_address!r}, "
            f"num_processes={num_processes!r}, process_id={process_id!r})"
        )
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = initialization_timeout
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


@functools.lru_cache(maxsize=8)
def _replicator(mesh: Mesh):
    """Jitted identity that replicates onto every device of ``mesh`` —
    memoized per mesh so repeated ``host_value`` calls reuse one compiled
    program instead of retracing a fresh closure each time."""
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec()))


def host_value(x) -> np.ndarray:
    """Host copy of a global array, valid in every process.

    Fully-addressable arrays (always the case single-process) and
    fully-replicated ones (every process holds a complete copy, even when
    other processes' replicas are non-addressable) fetch directly. An array
    sharded across non-addressable devices — the multi-controller case,
    where ``jax.device_get`` raises — is first replicated onto every device
    with a jitted identity (one ``all_gather`` over DCN), after which each
    process fetches its local replica. Verified by the 2-process run in
    ``parallel/multihost.py`` / ``tests/test_multihost.py``.
    """
    if getattr(x, "is_fully_addressable", True) or getattr(
        x, "is_fully_replicated", False
    ):
        return np.asarray(jax.device_get(x))
    from jax.sharding import NamedSharding

    sharding = x.sharding
    if not isinstance(sharding, NamedSharding):
        raise TypeError(
            "host_value needs a NamedSharding to replicate a "
            f"non-addressable array; got {type(sharding).__name__}"
        )
    return np.asarray(jax.device_get(_replicator(sharding.mesh)(x)))


@functools.lru_cache(maxsize=16)
def _packed_fetch_jit(mesh: Optional[Mesh]):
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        return jax.jit(
            lambda *arrays: jnp.concatenate([a.reshape(-1) for a in arrays])
        )
    replicated = NamedSharding(mesh, PartitionSpec())

    def pack(*arrays):
        # Replicate EACH operand before the concatenate, not just the
        # output: lowering `concatenate(sharded...)` straight into a
        # replicated output makes the SPMD partitioner reshard via a
        # masked sum, and operands that are replicated along an unmentioned
        # mesh axis (e.g. P('data') counters on a data×samples mesh) get
        # every replica summed in — the fetched counters came back
        # multiplied by the samples-axis size. Per-operand replication
        # lowers to plain all-gathers, after which the concat is local.
        return jnp.concatenate(
            [
                jax.lax.with_sharding_constraint(a.reshape(-1), replicated)
                for a in arrays
            ]
        )

    return jax.jit(pack, out_shardings=replicated)


def packed_host_fetch(arrays, mesh: Optional[Mesh] = None) -> np.ndarray:
    """ONE host transfer for several device arrays: flatten + concatenate on
    device, fetch once, caller slices the flat result apart.

    Each synchronous fetch on a remote-attached backend pays a full tunnel
    round-trip, so end-of-run values (counters, components, scalars) should
    ride together — this helper is the one audited home for the pattern
    (replication for multi-controller fetches, x64 so int64 payloads are not
    canonicalized to int32 at the jit boundary). Pass ``mesh`` when any
    input may span non-addressable devices: the packed result is then
    replicated and every process reads its local copy. Arrays should share
    a dtype (mixed dtypes would silently promote).
    """
    with jax.enable_x64(True):
        return np.asarray(host_value(_packed_fetch_jit(mesh)(*arrays)))


def device_put_global(x, sharding):
    """``jax.device_put`` that stays valid when ``sharding`` spans
    non-addressable devices (multi-controller runs).

    This jax's ``device_put`` of a host array onto a non-addressable
    sharding first runs ``multihost_utils.assert_equal`` — a REAL collective
    that (a) costs a cross-process round trip per call and (b) is
    unimplemented on the CPU backend, so the multihost rehearsal
    (``parallel/multihost.py``) crashed before ever dispatching. The ingest
    paths are SPMD by construction — every process computes identical host
    operands — so the equality collective buys nothing:
    ``make_array_from_callback`` assembles the global array from each
    process's local copy directly. Fully-addressable shardings (and bare
    devices / None) keep the plain fast path."""
    if sharding is None or getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx: x[idx]
    )


def local_shard(x) -> np.ndarray:
    """One addressable shard of a global array — a process-local synchronous
    fetch that works in single- and multi-controller modes alike (used for
    the eager-mode poke, where only the sync matters, not the value)."""
    shards = x.addressable_shards
    return np.asarray(shards[0].data) if shards else np.asarray(x)


def make_mesh(
    shape: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"data": 4, "samples": 2})``."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = [max(1, int(n)) for n in shape.values()]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {total} devices, have {len(devices)}"
        )
    grid = np.array(devices[:total]).reshape(sizes)
    return Mesh(grid, tuple(shape.keys()))


def default_mesh(
    num_reduce_partitions: Optional[int] = None,
    samples_axis: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """All available devices, data-major.

    ``num_reduce_partitions`` caps the data axis (the reference's reduce
    parallelism mapped onto the mesh); remaining devices are unused rather
    than silently changing semantics.
    """
    devices = list(devices if devices is not None else jax.devices())
    samples_axis = max(1, samples_axis)
    data = len(devices) // samples_axis
    if num_reduce_partitions is not None:
        data = max(1, min(data, num_reduce_partitions))
    return make_mesh({DATA_AXIS: data, SAMPLES_AXIS: samples_axis}, devices)


def parse_mesh_shape(spec: str) -> Dict[str, int]:
    """Parse the ``--mesh-shape`` flag: ``'data,samples'`` e.g. ``'4,2'``."""
    parts = [int(p) for p in spec.split(",")]
    if len(parts) == 1:
        parts.append(1)
    if len(parts) != 2:
        raise ValueError(f"--mesh-shape expects 'data,samples', got {spec!r}")
    return {DATA_AXIS: parts[0], SAMPLES_AXIS: parts[1]}


def resolve_run_mesh(
    mesh_shape: Optional[str] = None,
    num_reduce_partitions: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
):
    """The ONE run-mesh resolution rule (explicit ``--mesh-shape``, else
    all devices capped by ``--num-reduce-partitions``; ``None`` on one
    device) — shared by the PCA driver and the analyses so a change to
    the rule can never leave them resolving different meshes. ``devices``
    restricts the rule to a subset of the process's devices (an executor
    slice of the resident service — :func:`plan_executor_slices`); the
    default is every device, the historical behavior."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if mesh_shape:
        return make_mesh(parse_mesh_shape(mesh_shape), devices)
    if len(devices) == 1:
        return None
    return default_mesh(
        num_reduce_partitions=num_reduce_partitions, devices=devices
    )


# --------------------------------------------------------------------------
# Executor slices: partitioning one process's devices into independent
# sub-meshes (the resident service's concurrency unit).
# --------------------------------------------------------------------------

#: Job classes an executor slice may serve (the admission classes of
#: ``serve/queue.py``; spelled here so the device math has no serve import).
SLICE_SMALL = "small"
SLICE_LARGE = "large"


@dataclass(frozen=True)
class ExecutorSlice:
    """One independent executor: a contiguous range of the process's
    device list, its own mesh, its own worker thread, its own warm jit
    caches. Slices never share devices, so a whole-genome job on the
    large slice cannot head-block (or poison) a small-slice query — the
    isolation is by construction, not by scheduling discipline. Pure
    index arithmetic: the device-free plan validator reasons about slices
    without a backend, exactly like ``--plan-devices``."""

    name: str
    job_classes: Tuple[str, ...]
    device_start: int
    device_count: int

    def __post_init__(self) -> None:
        if self.device_count < 1:
            raise ValueError(
                f"slice {self.name!r} needs >= 1 device, got "
                f"{self.device_count}"
            )
        if not self.job_classes:
            raise ValueError(f"slice {self.name!r} serves no job class")

    def device_indices(self) -> Tuple[int, ...]:
        return tuple(
            range(self.device_start, self.device_start + self.device_count)
        )


def resolve_small_slices(spec, device_count: int) -> int:
    """The ``--executor-slices`` auto rule: ``'auto'`` (or ``None``) is one
    small slice whenever a device can be spared (>= 2 devices), zero on a
    single device (slicing one device buys nothing — the shared serial
    worker IS the right schedule there); an explicit integer passes
    through. ONE rule so the daemon and the load harness cannot drift."""
    if spec is None or spec == "auto":
        return 1 if int(device_count) >= 2 else 0
    count = int(spec)
    if count < 0:
        raise ValueError(f"--executor-slices must be >= 0, got {spec!r}")
    return count


def plan_executor_slices(
    device_count: int,
    small_slices: int = 0,
    small_slice_devices: int = 1,
) -> Tuple[ExecutorSlice, ...]:
    """Partition ``device_count`` devices into executor slices.

    ``small_slices == 0`` is the shared (historical) topology: ONE slice
    over every device serving both admission classes serially. Otherwise
    ``small_slices`` slices of ``small_slice_devices`` devices each are
    carved off the END of the device list for statically-bounded small
    jobs, and the remaining devices (at least one — a topology that
    starves the large class is an error, not a warning) form the large
    slice. Deterministic index math shared by the daemon (which maps
    indices onto ``jax.devices()``), admission (which validates each job
    against ITS slice's device count, not the whole pod's), and tests."""
    devices = int(device_count)
    small = int(small_slices)
    per_small = int(small_slice_devices)
    if devices < 1:
        raise ValueError(f"device_count must be >= 1, got {device_count}")
    if small < 0:
        raise ValueError(f"small_slices must be >= 0, got {small_slices}")
    if per_small < 1:
        raise ValueError(
            f"small_slice_devices must be >= 1, got {small_slice_devices}"
        )
    if small == 0:
        return (
            ExecutorSlice(
                name="shared",
                job_classes=(SLICE_SMALL, SLICE_LARGE),
                device_start=0,
                device_count=devices,
            ),
        )
    reserved = small * per_small
    if devices - reserved < 1:
        raise ValueError(
            f"{small} small slice(s) x {per_small} device(s) reserve "
            f"{reserved} of {devices} devices, leaving none for the large "
            "slice; shrink --executor-slices/--small-slice-devices or add "
            "devices"
        )
    slices = [
        ExecutorSlice(
            name="large",
            job_classes=(SLICE_LARGE,),
            device_start=0,
            device_count=devices - reserved,
        )
    ]
    for i in range(small):
        slices.append(
            ExecutorSlice(
                name=f"small-{i}",
                job_classes=(SLICE_SMALL,),
                device_start=devices - reserved + i * per_small,
                device_count=per_small,
            )
        )
    return tuple(slices)


__all__ = [
    "DATA_AXIS",
    "HOST_AXIS",
    "SAMPLES_AXIS",
    "PLATFORM_ENV",
    "HIER_HOSTS_ENV",
    "RING_PACK_MULTIPLE",
    "HOST_RUNTIME_BASELINE_BYTES",
    "DEFAULT_ICI_BYTES_PER_S",
    "DEFAULT_DCN_BYTES_PER_S",
    "LevelTraffic",
    "Topology",
    "parse_topology",
    "padded_cohort",
    "ring_traffic_bytes",
    "hierarchical_traffic_bytes",
    "flat_traffic_split",
    "resolve_reduce_schedule",
    "resolve_hier_hosts",
    "hierarchical_mesh",
    "host_peak_bytes",
    "apply_platform_override",
    "distributed_init",
    "host_value",
    "local_shard",
    "packed_host_fetch",
    "make_mesh",
    "default_mesh",
    "parse_mesh_shape",
    "resolve_run_mesh",
    "SLICE_SMALL",
    "SLICE_LARGE",
    "ExecutorSlice",
    "resolve_small_slices",
    "plan_executor_slices",
]

"""Multi-controller execution harness: a real ``jax.distributed`` run.

The reference's central operational capability is one job spanning machines —
a Spark cluster deployed with bdutil and addressed through a master URL
(``/root/reference/README.md:64-104``; ``GenomicsConf.scala:50-57``
``newSparkContext``). The TPU-native analog is multi-controller JAX: N
processes, each owning a slice of the device fleet, joined through a
coordinator into ONE global mesh, with every collective riding the same XLA
programs as the single-process path.

This module is the *executable proof* of that capability, not more plumbing:

- :func:`child_check` runs inside a coordinator-connected process and
  exercises the real pipeline: the data-parallel device-ingest accumulator
  over the global mesh (``ops/devicegen.py``), the finalize ``psum``-style
  cross-slice reduce, and the multi-controller fetch helpers
  (``parallel/mesh.py:host_value``). It asserts the global Gramian is
  bit-identical to the single-process host oracle *in this process*.
- :func:`verify_multihost` orchestrates the whole thing from one machine:
  spawns ``num_processes`` children with ``--coordinator-address
  127.0.0.1:<port> --num-processes N --process-id i`` and
  ``local_devices`` virtual CPU devices each (the same trick the test suite
  uses for a virtual mesh, ``tests/conftest.py``), collects each child's
  verdict, then re-runs the full ``variants-pca`` CLI across a fresh set of
  coordinator-connected processes and asserts all processes print identical
  principal components.

Run it directly to produce the machine-readable artifact::

    python -m spark_examples_tpu.parallel.multihost --artifact MULTIHOST.json

The same flags work against real multi-host TPU fleets (one process per
host, no ``--local-devices``): the child path calls the public
``distributed_init`` seam the driver itself uses (``config.py:init_distributed``).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

_CHILD_TAG = "MULTIHOST_CHILD "

# The small-but-real workload every child runs: the BRCA1 region of the
# flagship config (``SearchVariantsExampleBRCA1.scala:27``) over a cohort
# small enough for a few-second CPU run.
_REGION = "17:41196311:41277499"
_NUM_SAMPLES = 24
_SEED = 7
_SPACING = 100
_MIN_AF = 0.01

# The fleet-rehearsal region set: four equal-width windows, so the
# host-sharded contig split (``sharding/contig.py:partition_contigs_by_host``)
# has real work to balance and every process of a 2–4 host fleet ingests a
# strict subset of the cohort's sites.
_FLEET_REGIONS = ",".join(
    f"{ref}:41196311:41277499" for ref in ("17", "18", "19", "20")
)


def aggregate_host_counts(values) -> List[int]:
    """Sum small per-process host-side integer counters (I/O stats, ingest
    accounting) across every process of a ``jax.distributed`` run.

    The telemetry analog of the finalize ``psum``: each process's dataset
    layer counts only what ITS host loop streamed, so a whole-fleet manifest
    (``obs/manifest.py``) needs one cross-process reduction for its global
    I/O block. Rides ``process_allgather`` (host-local → global array over
    the same collectives the Gramian reduce uses), so stats parity holds on
    any backend the pipeline itself runs on; with one process it is a plain
    int cast, device-free — single-host runs pay nothing.
    """
    import numpy as np

    arr = np.asarray(list(values), dtype=np.int64)
    if arr.ndim != 1:
        raise ValueError(f"expected a flat counter vector, got shape {arr.shape}")
    import jax

    if jax.process_count() == 1:
        return [int(v) for v in arr]
    from jax.experimental import multihost_utils

    gathered = np.asarray(multihost_utils.process_allgather(arr))
    return [int(v) for v in gathered.reshape(jax.process_count(), -1).sum(axis=0)]


def child_check(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> Dict[str, object]:
    """Run the distributed Gramian check inside one coordinator-connected
    process; returns the verdict dict (also used as the child's JSON line).

    Initializes ``jax.distributed`` through the same seam the driver uses,
    builds the GLOBAL device mesh, streams the site grid through the
    data-parallel device-ingest accumulator (each data slice generating a
    disjoint grid span), reduces across slices, and compares against the
    packed-block host oracle computed independently in this process.
    """
    from spark_examples_tpu.parallel.mesh import distributed_init

    distributed_init(coordinator_address, num_processes, process_id)

    import jax
    import numpy as np

    from spark_examples_tpu.ops.devicegen import DeviceGenGramianAccumulator
    from spark_examples_tpu.parallel.mesh import default_mesh
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.synthetic import (
        SyntheticGenomicsSource,
        af_filter_micro,
    )

    source = SyntheticGenomicsSource(
        num_samples=_NUM_SAMPLES, seed=_SEED, variant_spacing=_SPACING
    )
    variant_set = "synthetic-variantset-1"
    mesh = default_mesh()
    accumulator = DeviceGenGramianAccumulator(
        num_samples=source.num_samples,
        vs_keys=[source.genotype_stream_key(variant_set)],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        min_af_micro=af_filter_micro(_MIN_AF),
        block_size=64,
        blocks_per_dispatch=2,
        exact_int=True,
        mesh=mesh,
        n_pops=source.n_pops,
    )
    name, start, end = _REGION.split(":")
    contig = Contig(name, int(start), int(end))
    k0, k1 = source.site_grid_range(contig)
    accumulator.add_grid(k0, k1)
    from spark_examples_tpu.parallel.mesh import host_value

    # One finalize reduction, probed for spans then fetched from the same
    # array (``accumulator.finalize()`` would re-run the cross-slice sum);
    # x64 so host_value's replicating jit keeps the promoted int64 result.
    with jax.enable_x64(True):
        gramian_device = accumulator.finalize_device()
        spans_processes = not bool(gramian_device.is_fully_addressable)
        gramian = host_value(gramian_device).astype(np.float64)
    per_set_rows, kept_sites = accumulator.ingest_counters()

    oracle = np.zeros((_NUM_SAMPLES, _NUM_SAMPLES), dtype=np.int64)
    for block in source.genotype_blocks(
        variant_set, contig, block_size=64, min_allele_frequency=_MIN_AF
    ):
        X = np.asarray(block["has_variation"], dtype=np.int64)
        oracle += X.T @ X

    # Second composition: RING ingest over a samples-only mesh spanning all
    # processes — every slice generates ONLY its own sample-column block and
    # the ``ppermute`` ring exchange (``ops/gramian.py:_ring_tiles``) crosses
    # the process boundary on every hop, which the single-process suite and
    # dryrun can never exercise for real.
    from spark_examples_tpu.ops.devicegen import DeviceGenRingGramianAccumulator
    from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS, make_mesh

    ring_mesh = make_mesh({SAMPLES_AXIS: jax.device_count()})
    ring = DeviceGenRingGramianAccumulator(
        num_samples=source.num_samples,
        vs_key=source.genotype_stream_key(variant_set),
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        mesh=ring_mesh,
        min_af_micro=af_filter_micro(_MIN_AF),
        block_size=64,
        blocks_per_dispatch=2,
        exact_int=True,
        n_pops=source.n_pops,
    )
    ring.add_grid(k0, k1)
    # One finalize reduction, probed for spans and fetched from the same
    # array (``ring.finalize()`` would rebuild + re-run the sharded sum).
    # ``finalize_sharded`` promotes the int32 shard accumulators' cross-slice
    # sum to int64 internally; the x64 block here is for ``host_value``,
    # whose replicating jit would otherwise canonicalize the int64 result
    # back to int32 on entry (matching ``finalize``'s own fetch).
    with jax.enable_x64(True):
        ring_sharded = ring.finalize_sharded()
        ring_spans = not bool(ring_sharded.is_fully_addressable)
        ring_full = host_value(ring_sharded)
    ring_gramian = ring_full[: source.num_samples, : source.num_samples]

    # Third composition: the SAME process-spanning samples ring under the
    # HIERARCHICAL schedule — ``reduce_schedule="hier"`` factors the
    # samples axis host-major (host factor = ``jax.process_count()``) and
    # runs the two-level tile exchange (``ops/gramian.py:_hier_ring_tiles``
    # inside ``ops/devicegen.py:_ring_update``), so the inner ring's hops
    # stay inside each process slice and only the outer stage crosses the
    # process boundary. Must be byte-identical to the flat ring above.
    hier = DeviceGenRingGramianAccumulator(
        num_samples=source.num_samples,
        vs_key=source.genotype_stream_key(variant_set),
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        mesh=ring_mesh,
        min_af_micro=af_filter_micro(_MIN_AF),
        block_size=64,
        blocks_per_dispatch=2,
        exact_int=True,
        n_pops=source.n_pops,
        reduce_schedule="hier",
    )
    hier.add_grid(k0, k1)
    hier_block = hier.schedule_block()
    with jax.enable_x64(True):
        hier_sharded = hier.finalize_sharded()
        hier_spans = not bool(hier_sharded.is_fully_addressable)
        hier_full = host_value(hier_sharded)
    hier_gramian = hier_full[: source.num_samples, : source.num_samples]

    # Telemetry parity: the run manifest's cross-process I/O aggregation
    # (``obs/manifest.py`` → :func:`aggregate_host_counts`) must reduce over
    # the same process set as the Gramian collectives — each process
    # contributes (process_id + 1, kept_sites) and every process must read
    # identical, correct global totals.
    aggregated = aggregate_host_counts([process_id + 1, int(kept_sites)])
    counts_ok = aggregated == [
        num_processes * (num_processes + 1) // 2,
        int(kept_sites) * num_processes,
    ]

    return {
        "process_id": process_id,
        "num_processes": num_processes,
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "mesh_shape": dict(mesh.shape),
        "platform": jax.default_backend(),
        "result_spans_processes": spans_processes,
        "gramian_ok": bool(np.array_equal(gramian.astype(np.int64), oracle)),
        "gramian_sum": int(gramian.sum()),
        "ring_mesh_shape": dict(ring_mesh.shape),
        "ring_spans_processes": ring_spans,
        "ring_gramian_ok": bool(
            np.array_equal(ring_gramian.astype(np.int64), oracle)
        ),
        "hier_schedule_kind": hier_block.get("kind"),
        "hier_spans_processes": hier_spans,
        "hier_gramian_ok": bool(
            np.array_equal(hier_gramian.astype(np.int64), oracle)
        ),
        "counter_aggregation_ok": bool(counts_ok),
        "variant_rows": [int(v) for v in per_set_rows],
        "kept_sites": int(kept_sites),
    }


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(local_devices: int) -> Dict[str, str]:
    """Environment for a spawned child: ``local_devices`` virtual CPU
    devices, CPU platform, no persistent compile cache. Any inherited device
    count flag (e.g. the test suite's 8) is replaced, not appended — XLA
    honors the first occurrence it parses."""
    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    # JAX_PLATFORMS alone is not enough on images whose sitecustomize hook
    # pins an accelerator platform at interpreter start; the package-level
    # override applies jax.config before the first client (parallel/mesh.py).
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_EXAMPLES_TPU_PLATFORM"] = "cpu"
    env["SPARK_EXAMPLES_TPU_NO_CACHE"] = "1"
    # Children must import this package from the repo, whatever the parent's
    # layout; keep the existing path (the TPU plugin site lives there).
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo_root + (os.pathsep + existing if existing else "")
    return env


def _run_children(
    commands: List[List[str]], env: Dict[str, str], timeout: float
) -> List[subprocess.CompletedProcess]:
    """Run coordinator-connected children concurrently and drain ALL their
    pipes in parallel: a sequential ``communicate()`` loop would deadlock if
    one child fills its pipe (verbose XLA/Gloo output, a large crash trace)
    while a sibling the parent is currently reading waits on it in a
    collective. A timed-out child yields a synthetic returncode -9 result
    instead of raising, so the caller's report survives."""
    from concurrent.futures import ThreadPoolExecutor

    procs = [
        subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True
        )
        for cmd in commands
    ]

    def drain(proc, cmd):
        try:
            out, err = proc.communicate(timeout=timeout)
            return subprocess.CompletedProcess(cmd, proc.returncode, out, err)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            return subprocess.CompletedProcess(
                cmd, -9, out, (err or "") + f"\n[timed out after {timeout}s]"
            )

    try:
        with ThreadPoolExecutor(max_workers=len(procs)) as pool:
            return list(pool.map(drain, procs, commands))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


def verify_multihost(
    num_processes: int = 2,
    local_devices: int = 4,
    timeout: float = 600.0,
    run_cli: bool = True,
) -> Dict[str, object]:
    """Spawn a real N-process ``jax.distributed`` run on localhost and verify
    it end to end; returns the machine-readable report.

    Phase 1 — ``child_check`` in every process: (a) data-parallel device
    ingest over the global mesh with the cross-slice finalize reduce,
    (b) RING ingest over a samples-only mesh whose ``ppermute`` hops cross
    the process boundary, and (c) the same ring under the HIERARCHICAL
    two-level schedule (host factor = process count); all three Gramians
    == host oracle, asserted per process.

    Phase 2 (``run_cli``) — :func:`_fleet_rehearsal`: the unmodified
    ``variants-pca`` CLI over a multi-contig region, solo (oracle) then as
    a coordinator-connected fleet with HOST-SHARDED ingest — each process
    reads only its contig partition (per-process I/O ~1/H of solo,
    manifest-asserted), PC rows byte-identical to solo, per-host
    conformance bounds hold, and the per-process flight-recorder segments
    merge into one valid Chrome trace.
    """
    env = _child_env(local_devices)
    port = _free_port()
    check_cmds = [
        [
            sys.executable,
            "-m",
            "spark_examples_tpu.parallel.multihost",
            "--child",
            "--coordinator-address",
            f"127.0.0.1:{port}",
            "--num-processes",
            str(num_processes),
            "--process-id",
            str(pid),
        ]
        for pid in range(num_processes)
    ]
    check_runs = _run_children(check_cmds, env, timeout)
    children: List[Dict[str, object]] = []
    for run in check_runs:
        verdict: Optional[Dict[str, object]] = None
        for line in run.stdout.splitlines():
            if line.startswith(_CHILD_TAG):
                verdict = json.loads(line[len(_CHILD_TAG):])
        if verdict is None:
            verdict = {
                "gramian_ok": False,
                "error": (run.stderr or "")[-2000:],
                "returncode": run.returncode,
            }
        children.append(verdict)
    gramian_ok = all(c.get("gramian_ok") for c in children) and all(
        r.returncode == 0 for r in check_runs
    )
    ring_ok = all(c.get("ring_gramian_ok") for c in children)
    hier_ok = all(
        c.get("hier_gramian_ok") and c.get("hier_schedule_kind") == "hier"
        for c in children
    )
    counts_ok = all(c.get("counter_aggregation_ok") for c in children)
    spans = all(
        c.get("result_spans_processes")
        and c.get("ring_spans_processes")
        and c.get("hier_spans_processes")
        for c in children
    )

    report: Dict[str, object] = {
        "num_processes": num_processes,
        "local_devices_per_process": local_devices,
        "children": children,
        "gramian_ok": gramian_ok,
        "ring_gramian_ok": ring_ok,
        "hier_gramian_ok": hier_ok,
        "counter_aggregation_ok": counts_ok,
        "result_spans_processes": spans,
    }

    if run_cli:
        report.update(_fleet_rehearsal(num_processes, env, timeout))
        report["ok"] = bool(
            gramian_ok
            and ring_ok
            and hier_ok
            and counts_ok
            and spans
            and report["cli_ok"]
            and report["cli_outputs_identical"]
            and report["fleet_host_sharded"]
            and report["fleet_io_ok"]
            and report["fleet_conformance_ok"]
            and report["fleet_trace_ok"]
        )
    else:
        report["ok"] = bool(
            gramian_ok and ring_ok and hier_ok and counts_ok and spans
        )
    return report


def _pc_rows(text: str) -> List[str]:
    """Emitted PC rows: ``<callset name>\\t<dataset>\\t<pc>...`` with the
    synthetic source's SxxNxxxxx naming (``sources/synthetic.py``) — the
    result surface of a run, independent of per-process telemetry lines
    (I/O stats, host-shard notices, Gloo rank banners) that legitimately
    differ between fleet members."""
    import re

    return [
        line for line in text.splitlines() if re.match(r"^S\d{2}N\d{5}\t", line)
    ]


def _fleet_rehearsal(
    num_processes: int, env: Dict[str, str], timeout: float
) -> Dict[str, object]:
    """The REAL multi-process full-pipeline rehearsal: the unmodified
    ``variants-pca`` CLI over a multi-contig region, run once solo (the
    byte-identity oracle) and once as an N-process coordinator-connected
    fleet with host-sharded ingest engaged.

    Asserts, machine-readably:

    - every process exits 0 and emits PC rows byte-identical to the solo
      oracle (``cli_outputs_identical`` — the merged Gramian is exact);
    - every process ingested a strict subset — per-process
      ``reference_bases`` ≤ ~1/H of solo (plus the one-contig overshoot
      the split rule allows), summing exactly to the solo total;
    - every process's manifest carries the cross-process global I/O block
      and a conformance block with no violated bound (the per-host
      ``host_peak_bytes`` pair included);
    - the per-process flight-recorder segments merge into ONE valid
      Chrome trace spanning every host (``obs/trace.py``).
    """
    import tempfile

    run_dir = tempfile.mkdtemp(prefix="multihost-fleet-")
    fleet_flags = [
        "variants-pca",
        "--source",
        "synthetic",
        "--num-samples",
        str(_NUM_SAMPLES),
        "--references",
        _FLEET_REGIONS,
    ]
    report: Dict[str, object] = {"fleet_run_dir": run_dir}

    solo_manifest_path = os.path.join(run_dir, "solo.manifest.json")
    solo_cmd = [
        sys.executable,
        "-m",
        "spark_examples_tpu",
        *fleet_flags,
        "--metrics-json",
        solo_manifest_path,
    ]
    t0 = time.perf_counter()
    solo = _run_children([solo_cmd], env, timeout)[0]
    solo_seconds = time.perf_counter() - t0
    solo_rows = _pc_rows(solo.stdout)

    port = _free_port()
    manifest_paths = [
        os.path.join(run_dir, f"fleet.{pid}.manifest.json")
        for pid in range(num_processes)
    ]
    cli_cmds = [
        [
            sys.executable,
            "-m",
            "spark_examples_tpu",
            *fleet_flags,
            "--coordinator-address",
            f"127.0.0.1:{port}",
            "--num-processes",
            str(num_processes),
            "--process-id",
            str(pid),
            "--metrics-json",
            manifest_paths[pid],
            "--trace-dir",
            run_dir,
        ]
        for pid in range(num_processes)
    ]
    t0 = time.perf_counter()
    cli_runs = _run_children(cli_cmds, env, timeout)
    fleet_seconds = time.perf_counter() - t0
    # Wall clocks ride along for the bench artifact (subprocess spawn +
    # compile included — the honest operator view of a cold fleet run, not
    # an ingest-only microbenchmark; the ingest-scaling claim rests on the
    # per-process reference_bases fractions below).
    report["fleet_wall_seconds"] = {
        "solo": round(solo_seconds, 3),
        "fleet": round(fleet_seconds, 3),
    }
    cli_ok = solo.returncode == 0 and all(
        run.returncode == 0 for run in cli_runs
    )
    fleet_rows = [_pc_rows(run.stdout) for run in cli_runs]
    identical = bool(solo_rows) and all(
        rows == solo_rows for rows in fleet_rows
    )
    report["cli_ok"] = cli_ok
    report["cli_outputs_identical"] = identical
    report["cli_pc_lines"] = len(solo_rows)
    if not cli_ok:
        report["cli_errors"] = [
            (run.stderr or "")[-2000:]
            for run in [solo, *cli_runs]
            if run.returncode
        ]
    report["fleet_host_sharded"] = all(
        "Host-sharded ingest: process" in run.stdout for run in cli_runs
    )

    manifests: List[Optional[Dict]] = []
    for path in manifest_paths:
        try:
            with open(path) as f:
                manifests.append(json.load(f))
        except (OSError, ValueError):
            manifests.append(None)
    solo_bases = 0
    try:
        with open(solo_manifest_path) as f:
            solo_bases = int(json.load(f)["io_stats"]["reference_bases"])
    except (OSError, ValueError, KeyError, TypeError):
        pass
    local_bases = [
        int((m or {}).get("io_stats", {}).get("reference_bases", -1))
        for m in manifests
    ]
    fractions = [
        (b / solo_bases if solo_bases > 0 else -1.0) for b in local_bases
    ]
    report["fleet_io_reference_bases"] = {
        "solo": solo_bases,
        "per_process": local_bases,
    }
    # Each host's declared-site share overshoots its 1/H fair share by at
    # most the one contig that closes its partition (the split rule's tie
    # walk) — with the four equal rehearsal windows that is ≤ 1/4 + a
    # rounding hair. The partition property itself is exact: the local
    # reads sum to the solo total, and the global block every process
    # aggregated collectively must equal it too.
    global_ok = all(
        int(
            ((m or {}).get("multihost") or {})
            .get("io_stats_global", {})
            .get("reference_bases", -1)
        )
        == solo_bases
        for m in manifests
    )
    report["fleet_io_ok"] = bool(
        solo_bases > 0
        and sum(local_bases) == solo_bases
        and all(0 <= f <= 1.0 / num_processes + 0.26 for f in fractions)
        and global_ok
    )

    conformance_ok = True
    for m in manifests:
        block = (m or {}).get("conformance")
        if not isinstance(block, dict):
            conformance_ok = False
            continue
        hostmem = block.get("hostmem")
        if not isinstance(hostmem, dict) or hostmem.get("ok") is not True:
            # The per-host bound pair must exist AND hold in every process.
            conformance_ok = False
        if any(
            isinstance(pair, dict) and pair.get("ok") is False
            for pair in block.values()
        ):
            conformance_ok = False
    report["fleet_conformance_ok"] = bool(conformance_ok)

    trace_errors: List[str]
    try:
        from spark_examples_tpu.obs.trace import (
            merge_run_trace,
            validate_chrome_trace,
        )

        doc = merge_run_trace(run_dir)
        trace_errors = list(validate_chrome_trace(doc))
        replicas = {
            e.get("args", {}).get("name", "")
            for e in doc.get("traceEvents", [])
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        if len(replicas) != num_processes:
            trace_errors.append(
                f"merged trace spans {len(replicas)} replicas, "
                f"expected {num_processes}: {sorted(replicas)}"
            )
    except Exception as e:  # pragma: no cover - diagnostic path
        trace_errors = [f"{type(e).__name__}: {e}"]
    report["fleet_trace_ok"] = not trace_errors
    if trace_errors:
        report["fleet_trace_errors"] = trace_errors[:20]
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="2-process jax.distributed verification run"
    )
    parser.add_argument("--child", action="store_true")
    parser.add_argument("--coordinator-address", default=None)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--process-id", type=int, default=0)
    parser.add_argument("--local-devices", type=int, default=4)
    parser.add_argument("--artifact", default=None)
    args = parser.parse_args(argv)

    if args.child:
        from spark_examples_tpu.parallel.mesh import apply_platform_override

        apply_platform_override()
        verdict = child_check(
            args.coordinator_address, args.num_processes, args.process_id
        )
        print(_CHILD_TAG + json.dumps(verdict), flush=True)
        return (
            0
            if verdict["gramian_ok"]
            and verdict["ring_gramian_ok"]
            and verdict["hier_gramian_ok"]
            and verdict["counter_aggregation_ok"]
            else 1
        )

    report = verify_multihost(
        num_processes=args.num_processes, local_devices=args.local_devices
    )
    print(json.dumps(report, indent=2))
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

from spark_examples_tpu.parallel.mesh import (
    DATA_AXIS,
    SAMPLES_AXIS,
    default_mesh,
    distributed_init,
    make_mesh,
)

__all__ = [
    "DATA_AXIS",
    "SAMPLES_AXIS",
    "default_mesh",
    "distributed_init",
    "make_mesh",
]

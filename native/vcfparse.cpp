// Native VCF data-plane parser: the file source's ingest hot loop.
//
// The reference's runtime was dominated by ingest (SURVEY.md §7); for local
// VCFs the analogous bottleneck is per-sample genotype parsing — a
// 2,504-sample cohort means thousands of GT fields per data line, which the
// pure-Python wire path builds as per-call objects (kept as the semantic
// oracle, sources/files.py). This translation unit feeds the PACKED ingest
// path instead: one pass over the decompressed VCF text emitting dense
// numpy-ready arrays — positions, ends, first-AF values, and the
// (line, sample) has-variation byte matrix the Gramian accumulator consumes
// directly (ops/gramian.py:add_rows).
//
// Contract (mirrors sources/files.py:_parse_vcf, tested for parity):
//   - 1-based POS becomes the half-open 0-based [start, start + len(REF));
//   - the GT subfield is located via the FORMAT column per line;
//   - an allele is "variation" iff its integer value is > 0; missing ('.')
//     alleles are not variation (VariantsPca.scala:67 semantics);
//   - AF is INFO's first AF= value (NaN when absent) so the
//     --min-allele-frequency filter (strictly greater, first value,
//     absent→drop) can run on the array;
//   - contig filtering/normalization stays in Python (per-contig row spans
//     are selected by the caller via the contig index arrays).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image); compiled
// on demand by spark_examples_tpu/utils/native.py.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <cstdlib>
#include <locale.h>

namespace {

struct Cursor {
    const char* p;
    const char* end;
};

// Advance to one past the next '\n' (or end).
inline const char* next_line(const char* p, const char* end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    return nl ? nl + 1 : end;
}

// [begin, end) of field `index` (tab-separated) within the line
// [line, line_end). Returns false when the line has too few fields.
inline bool field_span(const char* line, const char* line_end, int index,
                       const char** fb, const char** fe) {
    const char* p = line;
    for (int i = 0; i < index; ++i) {
        const char* tab = static_cast<const char*>(
            memchr(p, '\t', static_cast<size_t>(line_end - p)));
        if (!tab) return false;
        p = tab + 1;
    }
    const char* tab = static_cast<const char*>(
        memchr(p, '\t', static_cast<size_t>(line_end - p)));
    *fb = p;
    *fe = tab ? tab : line_end;
    return true;
}

// strtod honors LC_NUMERIC, so a host process that called setlocale() (e.g.
// a GUI embedding) would flip the decimal point and make the
// full-consumption check reject "0.5" — diverging from Python float() and
// silently dropping every AF-filtered record. Parse against a cached "C"
// locale instead; the grammar is then process-state-independent.
inline double strtod_c(const char* s, char** endp) {
    static locale_t c_loc = newlocale(LC_ALL_MASK, "C", nullptr);
    if (c_loc) return strtod_l(s, endp, c_loc);
    return strtod(s, endp);
}

inline int64_t parse_int(const char* b, const char* e, bool* ok) {
    int64_t v = 0;
    if (b == e) { *ok = false; return 0; }
    for (const char* p = b; p < e; ++p) {
        if (*p < '0' || *p > '9') { *ok = false; return 0; }
        v = v * 10 + (*p - '0');
    }
    *ok = true;
    return v;
}

}  // namespace

extern "C" {

// Count data lines and samples. Always returns 0; a buffer with no #CHROM
// header yields n_samples = 0 — the Python wire parser tolerates headerless
// (sites-only) VCFs as an empty cohort, and the native path must not reject
// what the oracle accepts (malformed DATA lines still fail in vcf_parse).
// Outputs: n_lines (data lines), n_samples.
int vcf_scan(const char* buf, int64_t len, int64_t* n_lines,
             int64_t* n_samples) {
    const char* p = buf;
    const char* end = buf + len;
    *n_lines = 0;
    *n_samples = -1;
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        if (line_end > p && *(line_end - 1) == '\r') --line_end;
        if (line_end == p) { p = next_line(p, end); continue; }
        if (p[0] == '#') {
            if (line_end - p >= 6 && memcmp(p, "#CHROM", 6) == 0) {
                // Samples are columns 10.. of the header row.
                int64_t tabs = 0;
                for (const char* q = p; q < line_end; ++q)
                    if (*q == '\t') ++tabs;
                *n_samples = tabs >= 9 ? tabs - 8 : 0;
            }
        } else {
            ++(*n_lines);
        }
        p = next_line(p, end);
    }
    if (*n_samples < 0) *n_samples = 0;
    return 0;
}

// Count data lines (non-empty, not starting with '#') in a buffer — the
// allocation bound for a chunked parse, where the #CHROM header (and so
// vcf_scan) lives in an earlier chunk.
int64_t vcf_count_data_lines(const char* buf, int64_t len) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t n = 0;
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        const char* stripped_end = line_end;
        if (stripped_end > p && *(stripped_end - 1) == '\r') --stripped_end;
        if (stripped_end > p && p[0] != '#') ++n;
        p = next_line(p, end);
    }
    return n;
}

// Span variant of vcf_count_data_lines: counts within [buf+begin, buf+end_off)
// — the per-chunk allocation bound of the chunk-parallel parse, which splits
// ONE shared buffer into line-aligned spans instead of copying per-thread
// slices.
int64_t vcf_count_data_lines_span(const char* buf, int64_t begin,
                                  int64_t end_off) {
    const char* p = buf + begin;
    const char* end = buf + end_off;
    int64_t n = 0;
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        const char* stripped_end = line_end;
        if (stripped_end > p && *(stripped_end - 1) == '\r') --stripped_end;
        if (stripped_end > p && p[0] != '#') ++n;
        p = next_line(p, end);
    }
    return n;
}

// Site-only scan: CHROM + [start, end) per data line, no INFO/GT walk — the
// cheap streaming pass behind lazy contig discovery (contig bounds for
// --all-references without paying the per-sample genotype parse). Arrays
// are caller-allocated with vcf_count_data_lines rows. Returns rows parsed,
// or the negative 1-based ordinal of the first malformed data line.
// Malformedness matches the Python parser exactly: a data line with fewer
// than 8 fields is rejected even though this scan only reads three of them
// (the fallback must not accept less than the native path, or vice versa).
int64_t vcf_scan_sites(const char* buf, int64_t len, int64_t* positions,
                       int64_t* ends, int64_t* contig_off,
                       int64_t* contig_len) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t row = 0;
    int64_t ordinal = 0;
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        const char* stripped_end = line_end;
        if (stripped_end > p && *(stripped_end - 1) == '\r') --stripped_end;
        if (stripped_end == p || p[0] == '#') { p = next_line(p, end); continue; }
        ++ordinal;
        const char *fb, *fe;
        if (!field_span(p, stripped_end, 0, &fb, &fe)) return -ordinal;
        contig_off[row] = fb - buf;
        contig_len[row] = fe - fb;
        if (!field_span(p, stripped_end, 1, &fb, &fe)) return -ordinal;
        bool ok = false;
        int64_t pos1 = parse_int(fb, fe, &ok);
        if (!ok || pos1 < 1) return -ordinal;
        positions[row] = pos1 - 1;
        if (!field_span(p, stripped_end, 3, &fb, &fe)) return -ordinal;
        ends[row] = positions[row] + (fe - fb);
        if (!field_span(p, stripped_end, 7, &fb, &fe)) return -ordinal;
        ++row;
        p = next_line(p, end);
    }
    return row;
}

// flags[i] = 1 iff row i's contig span differs in CONTENT from row i-1's
// (flags[0] = 1 when rows > 0). Lets the host decode one contig string per
// run instead of per row — the run detection is where the per-row Python
// cost was (rows are ~100% same-contig runs in sorted VCFs).
void vcf_mark_contig_changes(const char* buf, const int64_t* off,
                             const int64_t* len, int64_t rows,
                             int8_t* flags) {
    for (int64_t i = 0; i < rows; ++i) {
        if (i == 0) { flags[i] = 1; continue; }
        flags[i] = (len[i] != len[i - 1] ||
                    memcmp(buf + off[i], buf + off[i - 1],
                           static_cast<size_t>(len[i])) != 0)
                       ? 1
                       : 0;
    }
}

}  // extern "C"

namespace {

// Shared data-line parse core over [p, end): `base` anchors the emitted
// contig_off byte offsets (== p for a whole-buffer parse; the buffer start
// for a span parse, so every worker's offsets index ONE shared text and the
// host-side contig decode needs no per-span translation). Runs with the GIL
// released (ctypes CDLL), so concurrent span parses scale across cores.
int64_t parse_data_lines(const char* base, const char* p, const char* end,
                         int64_t n_samples, int64_t* positions, int64_t* ends,
                         double* af, int8_t* has_variation,
                         int64_t* contig_off, int64_t* contig_len) {
    const char* buf = base;
    int64_t row = 0;
    int64_t ordinal = 0;
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        const char* stripped_end = line_end;
        if (stripped_end > p && *(stripped_end - 1) == '\r') --stripped_end;
        if (stripped_end == p || p[0] == '#') { p = next_line(p, end); continue; }
        ++ordinal;

        const char *fb, *fe;
        // CHROM
        if (!field_span(p, stripped_end, 0, &fb, &fe)) return -ordinal;
        contig_off[row] = fb - buf;
        contig_len[row] = fe - fb;
        // POS (1-based) and REF length give [start, end).
        if (!field_span(p, stripped_end, 1, &fb, &fe)) return -ordinal;
        bool ok = false;
        int64_t pos1 = parse_int(fb, fe, &ok);
        if (!ok || pos1 < 1) return -ordinal;
        positions[row] = pos1 - 1;
        if (!field_span(p, stripped_end, 3, &fb, &fe)) return -ordinal;
        ends[row] = positions[row] + (fe - fb);
        // INFO: first AF= value.
        if (!field_span(p, stripped_end, 7, &fb, &fe)) return -ordinal;
        af[row] = NAN;
        for (const char* q = fb; q + 3 <= fe;) {
            bool at_start = (q == fb) || (*(q - 1) == ';');
            if (at_start && memcmp(q, "AF=", 3) == 0) {
                const char* vb = q + 3;
                const char* ve = vb;
                while (ve < fe && *ve != ';' && *ve != ',') ++ve;
                // Shared AF grammar (sources/files.py:af_float must match
                // bit for bit): trim ' '/'\t', then the value must be
                // 1..63 chars drawn from [0-9eE+-.] and fully strtod-
                // consumable. The charset gate closes every divergence
                // between strtod and Python float() (hex forms, digit
                // underscores, inf/nan words, exotic whitespace).
                while (vb < ve && (*vb == ' ' || *vb == '\t')) ++vb;
                while (ve > vb && (*(ve - 1) == ' ' || *(ve - 1) == '\t'))
                    --ve;
                char tmp[64];
                size_t n = static_cast<size_t>(ve - vb);
                bool charset_ok = n > 0;
                for (const char* c = vb; charset_ok && c < ve; ++c) {
                    char ch = *c;
                    charset_ok = (ch >= '0' && ch <= '9') || ch == '.' ||
                                 ch == '+' || ch == '-' || ch == 'e' ||
                                 ch == 'E';
                }
                if (charset_ok && n < sizeof(tmp)) {
                    memcpy(tmp, vb, n);
                    tmp[n] = '\0';
                    char* endp = nullptr;
                    double v = strtod_c(tmp, &endp);
                    if (endp == tmp + n) af[row] = v;
                }
                break;
            }
            const char* semi = static_cast<const char*>(
                memchr(q, ';', static_cast<size_t>(fe - q)));
            if (!semi) break;
            q = semi + 1;
        }
        // FORMAT: find the GT subfield index.
        int8_t* hv = has_variation + row * n_samples;
        memset(hv, 0, static_cast<size_t>(n_samples));
        const char *fmtb, *fmte;
        int gt_index = -1;
        if (field_span(p, stripped_end, 8, &fmtb, &fmte)) {
            int idx = 0;
            const char* q = fmtb;
            while (q <= fmte) {
                const char* colon = static_cast<const char*>(
                    memchr(q, ':', static_cast<size_t>(fmte - q)));
                const char* sub_end = colon ? colon : fmte;
                if (sub_end - q == 2 && q[0] == 'G' && q[1] == 'T') {
                    gt_index = idx;
                    break;
                }
                if (!colon) break;
                q = colon + 1;
                ++idx;
            }
            if (gt_index >= 0) {
                // Walk sample columns 9..9+n_samples-1.
                const char* s = fmte < stripped_end ? fmte + 1 : stripped_end;
                for (int64_t sample = 0;
                     sample < n_samples && s <= stripped_end; ++sample) {
                    const char* tab = static_cast<const char*>(memchr(
                        s, '\t', static_cast<size_t>(stripped_end - s)));
                    const char* col_end = tab ? tab : stripped_end;
                    // The GT subfield within this column.
                    const char* g = s;
                    for (int i = 0; i < gt_index && g; ++i) {
                        const char* colon = static_cast<const char*>(memchr(
                            g, ':', static_cast<size_t>(col_end - g)));
                        g = colon ? colon + 1 : nullptr;
                    }
                    if (g) {
                        const char* colon = static_cast<const char*>(memchr(
                            g, ':', static_cast<size_t>(col_end - g)));
                        const char* g_end = colon ? colon : col_end;
                        // Alleles separated by '/' or '|'; integer > 0 is
                        // variation; '.' (missing) is not.
                        int64_t allele = 0;
                        bool in_number = false;
                        for (const char* c = g; c <= g_end; ++c) {
                            if (c < g_end && *c >= '0' && *c <= '9') {
                                allele = allele * 10 + (*c - '0');
                                in_number = true;
                            } else {
                                if (in_number && allele > 0) {
                                    hv[sample] = 1;
                                    break;
                                }
                                allele = 0;
                                in_number = false;
                            }
                        }
                    }
                    if (!tab) break;
                    s = tab + 1;
                }
            }
        }
        ++row;
        p = next_line(p, end);
    }
    return row;
}

}  // namespace

extern "C" {

// Parse all data lines. Arrays are caller-allocated with n_lines rows (from
// vcf_scan): positions/ends int64, af double (NaN = absent),
// has_variation int8 (n_lines * n_samples, row-major), contig_off/contig_len
// int64 byte spans of the CHROM field within buf (Python decodes the
// strings). Returns the number of parsed lines, or the negative (1-based)
// line ordinal of the first malformed data line.
int64_t vcf_parse(const char* buf, int64_t len, int64_t n_samples,
                  int64_t* positions, int64_t* ends, double* af,
                  int8_t* has_variation, int64_t* contig_off,
                  int64_t* contig_len) {
    return parse_data_lines(buf, buf, buf + len, n_samples, positions, ends,
                            af, has_variation, contig_off, contig_len);
}

// Chunk-span entry point of the SAME core: parse the data lines of
// [buf+begin, buf+end_off) — a line-aligned span of one shared buffer. The
// chunk-parallel ingest engine calls this from a thread pool (the ctypes
// call releases the GIL), one span per worker, zero per-span copies;
// contig_off stays absolute into buf. The negative malformed-line ordinal
// is 1-based WITHIN the span.
int64_t vcf_parse_span(const char* buf, int64_t begin, int64_t end_off,
                       int64_t n_samples, int64_t* positions, int64_t* ends,
                       double* af, int8_t* has_variation, int64_t* contig_off,
                       int64_t* contig_len) {
    return parse_data_lines(buf, buf + begin, buf + end_off, n_samples,
                            positions, ends, af, has_variation, contig_off,
                            contig_len);
}

}  // extern "C"

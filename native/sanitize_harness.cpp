// Sanitizer replay harness for the native VCF parser.
//
// Compiled TOGETHER with vcfparse.cpp into a standalone executable (no
// Python, no ctypes) by utils/native.py:build_sanitizer_harness, under
// -fsanitize=address / undefined / thread. A standalone binary sidesteps
// the ASan/TSan runtime-preload problem of loading instrumented .so files
// into an uninstrumented CPython, and gives TSan a *real* multi-threaded
// exercise of the span entry points — the exact concurrency shape the
// chunk-parallel ingest engine runs them in (N threads, one shared
// read-only buffer, disjoint output arrays).
//
// Usage: harness CORPUS_FILE... — replays every corpus document through:
//   1. vcf_scan + vcf_parse            (whole-buffer parse)
//   2. vcf_count_data_lines + vcf_scan_sites + vcf_mark_contig_changes
//   3. vcf_parse_span / vcf_count_data_lines_span from SPAN_THREADS
//      concurrent threads over line-aligned spans of the shared buffer
//
// A malformed document is a VALID outcome (the parser reports the negative
// line ordinal; the Python layer raises) — the harness only fails on
// contract violations (row counts disagreeing with the pre-scan) and on
// whatever the sanitizer itself traps. Exit 0 = clean.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int vcf_scan(const char* buf, int64_t len, int64_t* n_lines,
             int64_t* n_samples);
int64_t vcf_parse(const char* buf, int64_t len, int64_t n_samples,
                  int64_t* positions, int64_t* ends, double* af,
                  int8_t* has_variation, int64_t* contig_off,
                  int64_t* contig_len);
int64_t vcf_count_data_lines(const char* buf, int64_t len);
int64_t vcf_count_data_lines_span(const char* buf, int64_t begin,
                                  int64_t end_off);
int64_t vcf_parse_span(const char* buf, int64_t begin, int64_t end_off,
                       int64_t n_samples, int64_t* positions, int64_t* ends,
                       double* af, int8_t* has_variation, int64_t* contig_off,
                       int64_t* contig_len);
int64_t vcf_scan_sites(const char* buf, int64_t len, int64_t* positions,
                       int64_t* ends, int64_t* contig_off,
                       int64_t* contig_len);
void vcf_mark_contig_changes(const char* buf, const int64_t* off,
                             const int64_t* len, int64_t rows, int8_t* flags);
}

namespace {

constexpr int kSpanThreads = 4;

struct ParseBuffers {
    std::vector<int64_t> positions, ends, contig_off, contig_len;
    std::vector<double> af;
    std::vector<int8_t> has_variation;
    void resize(int64_t rows, int64_t n_samples) {
        positions.resize(rows);
        ends.resize(rows);
        contig_off.resize(rows);
        contig_len.resize(rows);
        af.resize(rows);
        has_variation.assign(
            static_cast<size_t>(rows) *
                static_cast<size_t>(n_samples > 0 ? n_samples : 1),
            0);
    }
};

// Line-aligned spans of [0, len): each boundary sits one past a '\n'
// (mirrors sources/files.py:_line_aligned_spans).
std::vector<std::pair<int64_t, int64_t>> line_spans(const char* buf,
                                                    int64_t len, int n) {
    std::vector<std::pair<int64_t, int64_t>> spans;
    if (len == 0) return spans;
    int64_t target = (len + n - 1) / n;
    int64_t begin = 0;
    while (begin < len) {
        int64_t cut = begin + target < len ? begin + target : len;
        if (cut < len) {
            const void* nl = memchr(buf + cut - 1,
                                    '\n',
                                    static_cast<size_t>(len - cut + 1));
            cut = nl ? static_cast<const char*>(nl) - buf + 1 : len;
        }
        spans.emplace_back(begin, cut);
        begin = cut;
    }
    return spans;
}

int replay_document(const std::string& data, const char* name) {
    const char* buf = data.data();
    const int64_t len = static_cast<int64_t>(data.size());

    // 1. Whole-buffer scan + parse (the parse_vcf_arrays contract).
    int64_t n_lines = 0, n_samples = 0;
    vcf_scan(buf, len, &n_lines, &n_samples);
    ParseBuffers whole;
    whole.resize(n_lines, n_samples);
    int64_t parsed = vcf_parse(buf, len, n_samples, whole.positions.data(),
                               whole.ends.data(), whole.af.data(),
                               whole.has_variation.data(),
                               whole.contig_off.data(),
                               whole.contig_len.data());
    const bool malformed = parsed < 0;
    if (!malformed && parsed != n_lines) {
        fprintf(stderr, "%s: vcf_parse returned %lld of %lld scanned lines\n",
                name, (long long)parsed, (long long)n_lines);
        return 1;
    }

    // 2. Site-only scan + contig-run marking over its output.
    int64_t counted = vcf_count_data_lines(buf, len);
    if (counted != n_lines) {
        fprintf(stderr, "%s: count %lld != scan %lld\n", name,
                (long long)counted, (long long)n_lines);
        return 1;
    }
    ParseBuffers sites;
    sites.resize(counted, 0);
    int64_t site_rows = vcf_scan_sites(buf, len, sites.positions.data(),
                                       sites.ends.data(),
                                       sites.contig_off.data(),
                                       sites.contig_len.data());
    if (site_rows >= 0) {
        std::vector<int8_t> flags(static_cast<size_t>(site_rows) + 1);
        vcf_mark_contig_changes(buf, sites.contig_off.data(),
                                sites.contig_len.data(), site_rows,
                                flags.data());
    } else if (!malformed) {
        fprintf(stderr, "%s: sites scan rejected what vcf_parse accepted\n",
                name);
        return 1;
    }

    // 3. Concurrent span parses over the SHARED buffer — the TSan subject.
    auto spans = line_spans(buf, len, kSpanThreads);
    std::vector<ParseBuffers> outs(spans.size());
    std::vector<int64_t> span_rows(spans.size(), 0);
    std::vector<std::thread> threads;
    threads.reserve(spans.size());
    for (size_t i = 0; i < spans.size(); ++i) {
        threads.emplace_back([&, i] {
            int64_t rows = vcf_count_data_lines_span(buf, spans[i].first,
                                                     spans[i].second);
            outs[i].resize(rows, n_samples);
            span_rows[i] = vcf_parse_span(
                buf, spans[i].first, spans[i].second, n_samples,
                outs[i].positions.data(), outs[i].ends.data(),
                outs[i].af.data(), outs[i].has_variation.data(),
                outs[i].contig_off.data(), outs[i].contig_len.data());
        });
    }
    for (auto& t : threads) t.join();
    int64_t total = 0;
    bool span_malformed = false;
    for (int64_t rows : span_rows) {
        if (rows < 0) span_malformed = true;
        else total += rows;
    }
    if (!malformed && !span_malformed && total != n_lines) {
        fprintf(stderr, "%s: span parses total %lld != %lld serial rows\n",
                name, (long long)total, (long long)n_lines);
        return 1;
    }
    if (malformed != span_malformed) {
        fprintf(stderr, "%s: whole/span malformed-line disagreement\n", name);
        return 1;
    }
    return 0;
}

}  // namespace

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s corpus_file...\n", argv[0]);
        return 2;
    }
    int failures = 0;
    for (int i = 1; i < argc; ++i) {
        FILE* f = fopen(argv[i], "rb");
        if (!f) {
            fprintf(stderr, "cannot open %s\n", argv[i]);
            return 2;
        }
        std::string data;
        char chunk[1 << 16];
        size_t got;
        while ((got = fread(chunk, 1, sizeof chunk, f)) > 0)
            data.append(chunk, got);
        fclose(f);
        failures += replay_document(data, argv[i]);
    }
    if (failures) {
        fprintf(stderr, "%d corpus document(s) violated the parse contract\n",
                failures);
        return 1;
    }
    return 0;
}

#!/usr/bin/env bash
# CI gate, staged:
#   1. tier-1 tests — the exact command from ROADMAP.md, unchanged: exits
#      non-zero on any test failure and prints the DOTS_PASSED count the
#      growth driver tracks (this stage's semantics are a contract).
#   2. lint  — graftcheck lint (JAX-pitfall linter; the tree must be
#      clean or carry justified disables) + the mypy baseline gate
#      (skips with a notice when mypy is not installed).
#   3. sanitize (opt-in: `ci.sh --sanitize`) — ASAN/UBSAN/TSAN replay of
#      the VCF fuzz corpus against the native parser; skips gracefully
#      when no C++ compiler is available.
# Run from the repo root. Exit code: first failing stage wins, tier-1 first.
set -o pipefail

SANITIZE=0
for arg in "$@"; do
  case "$arg" in
    --sanitize) SANITIZE=1 ;;
    *) echo "ci.sh: unknown flag: $arg" >&2; exit 2 ;;
  esac
done

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

echo "== lint stage (graftcheck) =="
lint_rc=0
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck lint spark_examples_tpu || lint_rc=$?
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck typecheck || lint_rc=$?

san_rc=0
if [ "$SANITIZE" = "1" ]; then
  echo "== sanitizer stage (graftcheck sanitize) =="
  env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck sanitize || san_rc=$?
fi

if [ "$rc" -ne 0 ]; then exit "$rc"; fi
if [ "$lint_rc" -ne 0 ]; then exit "$lint_rc"; fi
exit "$san_rc"

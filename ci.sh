#!/usr/bin/env bash
# CI gate, staged:
#   1. tier-1 tests — the exact command from ROADMAP.md, unchanged: exits
#      non-zero on any test failure and prints the DOTS_PASSED count the
#      growth driver tracks (this stage's semantics are a contract).
#   2. lint  — graftcheck lint (JAX-pitfall linter; the tree must be
#      clean or carry justified disables) + the mypy baseline gate
#      (skips with a notice when mypy is not installed).
#   2b. ir — graftcheck ir (jaxpr-level audit of the real Gramian kernels:
#      ring overlap schedule, donation contract, packed-wire dtype flow,
#      jaxpr ring bytes == ring_traffic_bytes) + graftcheck lockgraph
#      (static lock-acquisition-order graph of the ingest/obs layer must
#      be acyclic and free of sync/queue-under-lock); the DOT graph
#      artifact is left under the stage's run dir (path echoed).
#   2c. ranges — graftcheck ranges (abstract-interpretation overflow &
#      exactness prover over the real kernel jaxprs: bf16/f32 per-dispatch
#      partials < 2^24, int32 accumulation < 2^31, lossy casts, contract
#      coverage, conversion-trigger conservativeness) across the full
#      mesh/dtype audit matrix — the Gramian dtype ladder is PROVEN on
#      every build, not asserted.
#   2c2. sched — graftcheck sched (device-free collective-schedule prover:
#      the schedule extracted from the traced kernel jaxprs is simulated
#      per link class over the topology matrix incl. the 32x8 pod —
#      per-level traffic == the closed forms, overlap clean, liveness in
#      budget) + the 4-virtual-device hier-vs-flat smoke: the same sharded
#      run through --reduce-schedule flat and hier (2 "hosts" x 2 devices
#      via SPARK_EXAMPLES_TPU_HIER_HOSTS) must produce byte-identical
#      result rows, valid manifest schedule blocks with predicted ==
#      measured ring bytes, and hier DCN bytes strictly below flat's.
#   2c3. multihost — a REAL 2-process x 2-virtual-device gloo fleet
#      (parallel/multihost.py): coordinator-connected child checks (global
#      mesh, cross-process ring, hierarchical ring) all byte-identical to
#      the host oracle, then the full variants-pca CLI as a fleet with
#      HOST-SHARDED ingest — per-process ingested reference bases ~1/H of
#      the solo oracle's (summing exactly to it), PC rows byte-identical
#      to solo, per-host conformance bounds ok in every process manifest,
#      and the per-process flight-recorder segments merged into one
#      validate_chrome_trace-clean Chrome trace.
#   2d. hostmem — graftcheck hostmem (AST host-memory audit: ZERO
#      findings and an EMPTY declared_unbounded inventory — the
#      escape-hatch era is over, GH006 flags the syntax itself) + the
#      --host-mem-budget smoke on the 4-virtual-device synthetic config
#      (a generous budget must plan OK, a 1 MiB budget must exit 2 — the
#      static bound, parallel/mesh.py:host_peak_bytes, is enforced, not
#      just printed) + the wire-ingest budget smoke: generated JSONL and
#      SAM inputs plan OK under an 8 GiB budget (the retired
#      "unprovable" class) and the JSONL run's measured peak RSS must
#      sit under its manifest's static bound.
#   3. obs smoke — a tiny synthetic PCA run with --metrics-json and a
#      1 s heartbeat; the produced run manifest must validate against the
#      schema (obs/manifest.py:validate_manifest), carry I/O stats, and
#      prove measured peak RSS <= the static host-memory bound (the
#      runtime half of the hostmem contract). A second tiny run with
#      --ingest packed --check-ranges asserts the manifest's
#      gramian_exactness pair: measured max |accumulator entry| <= the
#      statically-projected bound (the runtime half of the ranges
#      contract). Both runs must also carry the v2-additive conformance
#      block (prover-conformance pairs) with ok=true for hostmem (and
#      ranges on the second run); the sharded-ring smoke below asserts
#      the sched pair the same way.
#   4. sharded-ring smoke — a 4-virtual-device sharded run (tiny synthetic
#      cohort) twice: packed ring (--ring-pack-bits on) vs the unpacked
#      oracle (off). Result rows must be byte-identical and the manifests'
#      gramian_ring_bytes must show the >= 8x packed traffic reduction —
#      the ring path can never regress silently on a CPU-only runner.
#   4b. analyses smoke — the population-genetics analyses (analyses/) end
#      to end on CPU: plan entries accept valid GRM/LD/assoc configs and
#      exit-2 reject doomed ones; a tiny synthetic GRM run's kinship TSV
#      byte-compares against the full-matrix NumPy oracle; a 2-contig LD
#      prune is deterministic across runs and oracle-exact; an assoc scan
#      with a planted signal (phenotype = one site's carrier vector) ranks
#      that site top. Every run's manifest validates with the v2-additive
#      analysis block.
#   5. serve smoke — the resident daemon (serve/) end to end on CPU: start
#      `python -m spark_examples_tpu serve` with a synthetic source, assert
#      a plan-invalid request returns a structured 400 carrying the plan
#      finding, an accepted tiny job completes with a valid per-job
#      schema-v2 manifest, the identical resubmit reports a warm
#      compile-cache hit (hit counter >= 1 in /metrics), and SIGTERM
#      drains gracefully: the in-flight job finishes, new jobs get 503,
#      the daemon exits 0.
#   5b. serve concurrency smoke — the executor-slice daemon on 4 virtual
#      CPU devices (--executor-slices 1): a small job (via the
#      `submit --wait` verb) completes WHILE a large job is still on the
#      large slice (no head-of-line blocking); a second large job queued
#      mid-run survives `kill -9` of the daemon — the restarted daemon
#      replays the job journal, finishes the queued job, fails the
#      mid-device job with a structured daemon-restarted error, and
#      serves a repeat-geometry job warm from the run-dir persistent
#      state. Then the serve-load harness (bench.py --config serve-load)
#      drives mixed traffic through the HTTP API and asserts small-job
#      P99 under concurrent large-job load stays within ~2x its unloaded
#      P99 and below the large job's wall-clock.
#   5c. multi-replica serving smoke — two replica daemons (--replica-id
#      a/b) on ONE run dir: a large job lands on a, whose fault plan
#      SIGKILLs it the moment device work begins (`kill -9 ... mid-
#      device`); small jobs keep flowing through b throughout; b steals
#      the orphaned job under a fencing epoch and — per the journaled
#      device_began rule — settles it with the structured
#      replica-failover error instead of silently re-running the
#      devices; the comma-separated client endpoint list fails over off
#      the dead replica; the run dir's flight-recorder segments + journal
#      are then merged by `trace export` into one Chrome-trace JSON that
#      must validate well-formed (obs/trace.py:validate_chrome_trace) with
#      the stolen job's span tree complete across BOTH replica processes:
#      the killed owner's span closed as truncated, a whole steal flow
#      arrow, lease epochs and the fenced terminal state present, zero
#      orphan spans; `graftcheck lockgraph` stays acyclic with the
#      lease-substrate locks. Then the full two-replica chaos matrix
#      (tests/test_serve_replicas_chaos.py): SIGKILL at every registered
#      serve kill-point, survivor results byte-compared against a
#      single-replica oracle.
#   6. faults — the robustness smoke, CPU-pinned: an oracle run, the same
#      run SIGKILLed by a deterministic fault plan at the
#      checkpoint.post-save kill-point (exit must be 137), then
#      --resume-from — resumed eigenvectors must be byte-identical to the
#      oracle and the manifest's resume block must show a real
#      fast-forward. Then the serve watchdog end to end in-process: an
#      injected worker crash mid-job must leave the job `failed` with a
#      structured worker-crashed error, the daemon healthy, the next job
#      completing, and the drain clean.
#   7. sanitize (opt-in: `ci.sh --sanitize`) — ASAN/UBSAN/TSAN replay of
#      the VCF fuzz corpus against the native parser; skips gracefully
#      when no C++ compiler is available.
# Run from the repo root. Exit code: first failing stage wins, tier-1 first.
set -o pipefail

SANITIZE=0
for arg in "$@"; do
  case "$arg" in
    --sanitize) SANITIZE=1 ;;
    *) echo "ci.sh: unknown flag: $arg" >&2; exit 2 ;;
  esac
done

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

echo "== lint stage (graftcheck) =="
lint_rc=0
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck lint spark_examples_tpu || lint_rc=$?
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck typecheck || lint_rc=$?

echo "== proto stage (graftcheck proto: replica-protocol model checking) =="
proto_rc=0
# The declared 2-replica / 2-job / 2-crash matrix, exhaustively (the
# report echoes its bounds and explored-state count). stalls=0 here;
# the lease expiry/steal/adoption dimension follows at jobs=1 —
# together the two exhaustive runs reach every transition type the
# model has (see check/proto.py:check_protocol).
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck proto || proto_rc=$?
PROTO_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck proto \
  --jobs 1 --stalls 2 --json > "$PROTO_TMP/stall.json" || proto_rc=$?
env JAX_PLATFORMS=cpu python - "$PROTO_TMP/stall.json" <<'PYEOF' || proto_rc=$?
import json, sys
doc = json.load(open(sys.argv[1]))
bounds = ", ".join(f"{k}={v}" for k, v in sorted(doc["bounds"].items()))
if not doc["exhausted"] or doc["states"] <= 0:
    print(f"proto stall run NOT exhaustive at [{bounds}]"); sys.exit(1)
if doc["uncovered_windows"]:
    print("proto stall run uncovered crash windows:",
          doc["uncovered_windows"]); sys.exit(1)
if not doc["ok"]:
    print("proto stall run findings:")
    for f in doc["findings"]:
        print(" ", f)
    sys.exit(1)
print(f"proto stall run OK: {doc['states']} states explored at "
      f"[{bounds}], 0 findings, 0 uncovered crash windows")
PYEOF
rm -rf "$PROTO_TMP"
# The checker's own test suite: every planted single-decision protocol
# bug must be caught by its matching GP rule at its witness bounds.
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck proto \
  --mutations || proto_rc=$?

echo "== ir stage (graftcheck ir + lockgraph) =="
ir_rc=0
IR_TMP=$(mktemp -d /tmp/graftcheck-ir.XXXXXX)
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck ir || ir_rc=$?
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck lockgraph \
  --dot "$IR_TMP/lockgraph.dot" || ir_rc=$?
if [ -s "$IR_TMP/lockgraph.dot" ]; then
  echo "lock-order DOT artifact: $IR_TMP/lockgraph.dot"
else
  echo "lockgraph DOT artifact missing"; ir_rc=1
fi

echo "== ranges stage (graftcheck ranges) =="
rg_rc=0
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck ranges || rg_rc=$?

echo "== sched stage (graftcheck sched + hier-vs-flat smoke) =="
sched_rc=0
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck sched || sched_rc=$?
SCHED_TMP=$(mktemp -d)
# Hier-vs-flat parity on 4 virtual devices: the same sharded run through
# the flat ring and the two-level schedule (2 "hosts" x 2 devices via the
# rehearsal override) must produce BYTE-IDENTICAL result rows, and both
# manifests must carry a valid schedule block whose predicted bytes match
# the per-flush accounting (delta 0 on an all-packed run).
sched_flags="--num-samples 64 --references 1:0:400000 --mesh-shape 1,4 \
  --similarity-strategy sharded --block-size 64 --ingest packed"
for mode in flat hier; do
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_PLATFORM=cpu \
      SPARK_EXAMPLES_TPU_NO_CACHE=1 SPARK_EXAMPLES_TPU_HIER_HOSTS=2 \
      XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m spark_examples_tpu variants-pca $sched_flags \
      --reduce-schedule "$mode" --metrics-json "$SCHED_TMP/$mode.json" \
      > "$SCHED_TMP/$mode.out" 2> "$SCHED_TMP/$mode.err" || sched_rc=$?
done
if [ "$sched_rc" -eq 0 ]; then
  grep -P "\t" "$SCHED_TMP/flat.out" > "$SCHED_TMP/flat.tsv"
  grep -P "\t" "$SCHED_TMP/hier.out" > "$SCHED_TMP/hier.tsv"
  if ! cmp -s "$SCHED_TMP/flat.tsv" "$SCHED_TMP/hier.tsv"; then
    echo "hier result rows DIFFER from the flat-ring oracle"
    sched_rc=1
  fi
fi
if [ "$sched_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$SCHED_TMP/flat.json" "$SCHED_TMP/hier.json" <<'PYEOF' || sched_rc=$?
import sys
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
docs = {}
for path in sys.argv[1:3]:
    doc = read_manifest(path)
    errors = validate_manifest(doc)
    if errors:
        print("schedule manifest INVALID:\n  " + "\n  ".join(errors))
        sys.exit(1)
    docs[path] = doc["schedule"]
flat, hier = docs[sys.argv[1]], docs[sys.argv[2]]
for name, blk in (("flat", flat), ("hier", hier)):
    if blk is None:
        print(f"{name} run carries no schedule block"); sys.exit(1)
    if blk["predicted_ring_bytes"] != blk["measured_ring_bytes"]:
        print(f"{name} predicted != measured ring bytes: {blk}"); sys.exit(1)
if flat["kind"] != "flat" or hier["kind"] != "hier":
    print(f"schedule kinds wrong: {flat['kind']}/{hier['kind']}"); sys.exit(1)
if not (0 < hier["predicted_dcn_bytes"] < flat["predicted_dcn_bytes"]):
    print("hier DCN bytes not strictly below flat DCN bytes: "
          f"hier={hier['predicted_dcn_bytes']} flat={flat['predicted_dcn_bytes']}")
    sys.exit(1)
print(f"sched smoke OK: hier==flat rows byte-identical, predicted==measured, "
      f"DCN {flat['predicted_dcn_bytes']} -> {hier['predicted_dcn_bytes']} B "
      f"({flat['predicted_dcn_bytes'] / hier['predicted_dcn_bytes']:.1f}x less "
      "on the slow link)")
PYEOF
else
  echo "sched smoke failed (rc=$sched_rc):"; tail -20 "$SCHED_TMP"/*.err
fi
rm -rf "$SCHED_TMP"

echo "== multihost stage (2-process gloo fleet: host-sharded ingest parity) =="
mh_rc=0
MH_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu python -m spark_examples_tpu.parallel.multihost \
    --num-processes 2 --local-devices 2 --artifact "$MH_TMP/report.json" \
    > "$MH_TMP/report.out" 2> "$MH_TMP/report.err" || mh_rc=$?
if [ "$mh_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$MH_TMP/report.json" <<'PYEOF' || mh_rc=$?
import json, sys
doc = json.load(open(sys.argv[1]))
checks = ("gramian_ok", "ring_gramian_ok", "hier_gramian_ok",
          "result_spans_processes", "cli_ok", "cli_outputs_identical",
          "fleet_host_sharded", "fleet_io_ok", "fleet_conformance_ok",
          "fleet_trace_ok", "ok")
bad = [k for k in checks if doc.get(k) is not True]
if bad:
    print(f"multihost report failed checks: {bad}")
    print(json.dumps({k: doc.get(k) for k in checks}))
    sys.exit(1)
bases = doc["fleet_io_reference_bases"]
solo, per = bases["solo"], bases["per_process"]
H = doc["num_processes"]
# ~1/H of solo per process: the fair share plus at most the one contig
# that closes a partition (the split rule's documented overshoot), and
# the partition property exact — local reads sum to the solo total.
if sum(per) != solo or any(
        not (0 < b <= solo * (1.0 / H + 0.26)) for b in per):
    print(f"per-process ingest not ~1/{H} of solo: {per} vs {solo}")
    sys.exit(1)
shares = [round(b / solo, 3) for b in per]
print(f"multihost smoke OK: {H} processes, PC rows byte-identical to the "
      f"solo oracle, per-host ingest {shares} of solo ({solo} bases), "
      "hier ring exact, merged fleet trace valid")
PYEOF
else
  echo "multihost fleet run failed (rc=$mh_rc):"
  tail -20 "$MH_TMP/report.err"; tail -5 "$MH_TMP/report.out"
fi
rm -rf "$MH_TMP"

echo "== hostmem stage (graftcheck hostmem + host-memory budget) =="
hm_rc=0
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck hostmem || hm_rc=$?
# TOTAL: the declared-unbounded inventory must be EMPTY — a hatch is a
# GH006 finding now, and this assert catches any report-plumbing drift.
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck hostmem --json \
  | python -c '
import json, sys
doc = json.load(sys.stdin)
if doc["declared_unbounded"] != []:
    print("hostmem inventory NOT empty:", doc["declared_unbounded"])
    sys.exit(1)
if doc["finding_count"] != 0:
    print("hostmem findings present:", doc["findings"]); sys.exit(1)
print("hostmem totality OK (0 findings, declared_unbounded == [])")
' || hm_rc=$?
hm_flags="--num-samples 64 --references 1:0:400000 --mesh-shape 1,4 \
  --similarity-strategy sharded --block-size 64 --plan-devices 4"
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck plan $hm_flags \
  --host-mem-budget 8589934592 > /dev/null || {
    echo "hostmem budget smoke: in-budget plan REJECTED"; hm_rc=1; }
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck plan $hm_flags \
  --host-mem-budget 1048576 > /dev/null
if [ "$?" -ne 2 ]; then
  echo "hostmem budget smoke: over-budget plan did not exit 2"; hm_rc=1
else
  echo "hostmem budget smoke OK (in-budget plan OK, over-budget exit 2)"
fi

# Wire-ingest budget smoke: JSONL and SAM inputs under --host-mem-budget
# were the exit-2 "unprovable" class; with the total resolver a real file
# proves a tight bound from its bytes on disk and the plan exits 0. The
# JSONL conf then RUNS, and its manifest's measured peak RSS must sit
# under the same static bound the plan proved (the e2e conformance leg).
WIRE_TMP=$(mktemp -d)
python - "$WIRE_TMP" <<'PYEOF'
import json, sys
root = sys.argv[1]
with open(f"{root}/cohort.jsonl", "w") as f:
    for i in range(64):
        f.write(json.dumps({
            "referenceName": "17", "start": 100 + 10 * i, "end": 101 + 10 * i,
            "referenceBases": "A", "alternateBases": ["G"],
            "info": {"AF": ["0.5"]},
            "calls": [
                {"callSetId": f"j-{s}", "callSetName": f"S{s}",
                 "genotype": [1, 0] if (i + s) % 2 else [0, 0]}
                for s in range(4)
            ],
        }) + "\n")
with open(f"{root}/reads.sam", "w") as f:
    f.write("@HD\tVN:1.6\n@SQ\tSN:21\tLN:48129895\n")
    for i in range(20):
        f.write(f"r{i:03d}\t0\t21\t{1000 + 5 * i}\t60\t40M\t*\t0\t0\t"
                f"{'ACGT' * 10}\t{'F' * 40}\n")
PYEOF
for wire_input in "$WIRE_TMP/cohort.jsonl" "$WIRE_TMP/reads.sam"; do
  env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck plan \
    --source file --input-files "$wire_input" --ingest wire \
    --num-samples 4 --references 17:0:1000 \
    --host-mem-budget 8589934592 > /dev/null || {
      echo "wire budget smoke: $(basename "$wire_input") plan not provable"
      hm_rc=1; }
done
wire_rc=0
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu variants-pca \
    --source file --input-files "$WIRE_TMP/cohort.jsonl" --ingest wire \
    --references 17:0:1000 --metrics-json "$WIRE_TMP/manifest.json" \
    > /dev/null 2> "$WIRE_TMP/wire.err" || wire_rc=$?
if [ "$wire_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$WIRE_TMP/manifest.json" <<'PYEOF' || hm_rc=$?
import sys
from spark_examples_tpu.obs.manifest import read_manifest
hm = read_manifest(sys.argv[1])["host_memory"]
if not hm["peak_rss_bytes"] or not hm["static_bound_bytes"]:
    print(f"wire manifest host_memory incomplete: {hm}"); sys.exit(1)
if hm["peak_rss_bytes"] > hm["static_bound_bytes"]:
    print("wire run measured peak RSS EXCEEDS the static bound: "
          f"{hm['peak_rss_bytes']} > {hm['static_bound_bytes']}")
    sys.exit(1)
print(f"wire budget smoke OK (JSONL+SAM provable; measured "
      f"{hm['peak_rss_bytes'] >> 20} MiB <= bound "
      f"{hm['static_bound_bytes'] >> 20} MiB)")
PYEOF
else
  echo "wire budget smoke run failed (rc=$wire_rc):"
  tail -10 "$WIRE_TMP/wire.err"; hm_rc=1
fi
rm -rf "$WIRE_TMP"

echo "== observability smoke (run manifest schema) =="
obs_rc=0
OBS_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu variants-pca \
    --num-samples 8 --references 1:0:50000 \
    --metrics-json "$OBS_TMP/manifest.json" --heartbeat-seconds 1 \
    > "$OBS_TMP/stdout.log" 2> "$OBS_TMP/stderr.log" || obs_rc=$?
if [ "$obs_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$OBS_TMP/manifest.json" <<'PYEOF' || obs_rc=$?
import sys
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
doc = read_manifest(sys.argv[1])
errors = validate_manifest(doc)
if errors:
    print("manifest INVALID:\n  " + "\n  ".join(errors))
    sys.exit(1)
if doc["io_stats"] is None or doc["io_stats"]["variants"] <= 0:
    print("manifest has no I/O stats from the smoke run")
    sys.exit(1)
hm = doc["host_memory"]
if not hm["peak_rss_bytes"] or not hm["static_bound_bytes"]:
    print(f"manifest host_memory incomplete: {hm}")
    sys.exit(1)
if hm["peak_rss_bytes"] > hm["static_bound_bytes"]:
    print("measured peak RSS EXCEEDS the static host-memory bound: "
          f"{hm['peak_rss_bytes']} > {hm['static_bound_bytes']} "
          "(parallel/mesh.py:host_peak_bytes no longer describes reality)")
    sys.exit(1)
conf = (doc.get("conformance") or {}).get("hostmem")
if not conf or conf.get("ok") is not True:
    print(f"manifest conformance block missing/failed for hostmem: {conf}")
    sys.exit(1)
print(f"manifest OK ({len(doc['metrics'])} metrics, "
      f"{len(doc['spans'])} root spans; host peak RSS "
      f"{hm['peak_rss_bytes'] >> 20} MiB <= bound "
      f"{hm['static_bound_bytes'] >> 20} MiB; hostmem conformance ok)")
PYEOF
else
  echo "obs smoke run failed (rc=$obs_rc):"; tail -20 "$OBS_TMP/stderr.log"
fi
if [ "$obs_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    python -m spark_examples_tpu variants-pca \
      --num-samples 8 --references 1:0:50000 \
      --ingest packed --check-ranges \
      --metrics-json "$OBS_TMP/ranges.json" \
      > /dev/null 2> "$OBS_TMP/ranges.err" || obs_rc=$?
  if [ "$obs_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$OBS_TMP/ranges.json" <<'PYEOF' || obs_rc=$?
import sys
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
doc = read_manifest(sys.argv[1])
errors = validate_manifest(doc)
if errors:
    print("check-ranges manifest INVALID:\n  " + "\n  ".join(errors))
    sys.exit(1)
ge = doc.get("gramian_exactness")
if not ge or ge.get("entry_max") is None or not ge.get("static_entry_bound"):
    print(f"--check-ranges run carries no gramian_exactness pair: {ge}")
    sys.exit(1)
if ge["entry_max"] > ge["static_entry_bound"]:
    print("measured accumulator entry EXCEEDS the static bound: "
          f"{ge['entry_max']} > {ge['static_entry_bound']} "
          "(the GR005-proven projection no longer describes reality)")
    sys.exit(1)
conf = doc.get("conformance") or {}
for prover in ("hostmem", "ranges"):
    pair = conf.get(prover)
    if not pair or pair.get("ok") is not True:
        print(f"conformance pair missing/failed for {prover}: {pair}")
        sys.exit(1)
print(f"check-ranges smoke OK (entry max {ge['entry_max']} <= "
      f"projected bound {ge['static_entry_bound']}; hostmem+ranges "
      "conformance ok)")
PYEOF
  else
    echo "check-ranges smoke run failed (rc=$obs_rc):"
    tail -20 "$OBS_TMP/ranges.err"
  fi
fi
rm -rf "$OBS_TMP"

echo "== sharded-ring smoke (4 virtual devices, packed vs oracle) =="
ring_rc=0
RING_TMP=$(mktemp -d)
# N=64 over a samples axis of 4 keeps the local width (16) a multiple of 8
# in BOTH wire formats, so the two runs do identical work and the traffic
# ratio is exactly 8 (no ragged-byte slack in the assertion).
ring_flags="--num-samples 64 --references 1:0:400000 --mesh-shape 1,4 \
  --similarity-strategy sharded --block-size 64"
for mode in on off; do
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_PLATFORM=cpu \
      SPARK_EXAMPLES_TPU_NO_CACHE=1 \
      XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m spark_examples_tpu variants-pca $ring_flags \
      --ring-pack-bits "$mode" --metrics-json "$RING_TMP/$mode.json" \
      > "$RING_TMP/$mode.out" 2> "$RING_TMP/$mode.err" || ring_rc=$?
done
if [ "$ring_rc" -eq 0 ]; then
  # Result rows only (lines with tabs): the manifest-path echo differs.
  grep -P "\t" "$RING_TMP/on.out" > "$RING_TMP/on.tsv"
  grep -P "\t" "$RING_TMP/off.out" > "$RING_TMP/off.tsv"
  if ! cmp -s "$RING_TMP/on.tsv" "$RING_TMP/off.tsv"; then
    echo "packed ring result rows DIFFER from the --ring-pack-bits off oracle"
    ring_rc=1
  fi
fi
if [ "$ring_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$RING_TMP/on.json" "$RING_TMP/off.json" <<'PYEOF' || ring_rc=$?
import sys
from spark_examples_tpu.obs.manifest import manifest_metric_value, read_manifest
from spark_examples_tpu.obs.metrics import GRAMIAN_RING_BYTES
packed, oracle = (
    manifest_metric_value(read_manifest(path), GRAMIAN_RING_BYTES)
    for path in sys.argv[1:3]
)
if not packed or not oracle:
    print(f"manifest missing {GRAMIAN_RING_BYTES} (packed={packed}, oracle={oracle})")
    sys.exit(1)
if oracle < 8 * packed:
    print(f"packed ring traffic not >= 8x smaller: packed={packed} oracle={oracle}")
    sys.exit(1)
for path in sys.argv[1:3]:
    pair = (read_manifest(path).get("conformance") or {}).get("sched")
    if not pair or pair.get("ok") is not True:
        print(f"sched conformance pair missing/failed in {path}: {pair}")
        sys.exit(1)
print(f"ring smoke OK: parity exact, ring bytes {int(oracle)} -> {int(packed)} "
      f"({oracle / packed:.1f}x reduction)")
PYEOF
else
  echo "sharded-ring smoke failed (rc=$ring_rc):"; tail -20 "$RING_TMP"/*.err
fi
rm -rf "$RING_TMP"

echo "== analyses smoke (GRM oracle, LD determinism, assoc signal) =="
an_rc=0
AN_TMP=$(mktemp -d)
an_flags="--num-samples 8 --references 1:0:60000"

# Plan entries: every analysis verb validates device-free, and a doomed
# configuration is an exit-2 reject (the admission contract of analyses/).
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck plan \
  --analysis grm $an_flags > /dev/null || {
    echo "analyses smoke: grm plan REJECTED"; an_rc=1; }
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck plan \
  --analysis ld $an_flags > /dev/null || {
    echo "analyses smoke: ld plan REJECTED"; an_rc=1; }
env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck plan \
  --analysis ld $an_flags --ld-r2-threshold 1.5 > /dev/null 2>&1
if [ "$?" -ne 2 ]; then
  echo "analyses smoke: bad LD threshold did not exit 2"; an_rc=1
fi

# 1. GRM: tiny synthetic CLI run; the written kinship TSV must be
#    BYTE-IDENTICAL to the full-matrix NumPy oracle over the same stream,
#    and the manifest must validate with the analysis block.
grm_rc=0
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu grm $an_flags \
    --grm-out "$AN_TMP/kin.tsv" --metrics-json "$AN_TMP/grm.json" \
    > "$AN_TMP/grm.out" 2> "$AN_TMP/grm.err" || grm_rc=$?
if [ "$grm_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$AN_TMP" $an_flags <<'PYEOF' || grm_rc=$?
import sys
import numpy as np
from spark_examples_tpu.analyses.grm import format_grm_rows, grm_reference
from spark_examples_tpu.config import GrmConf
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
from spark_examples_tpu.pipeline.pca_driver import make_source

tmp, flags = sys.argv[1], sys.argv[2:]
conf = GrmConf.parse(flags)
src = make_source(conf)
names = [cs["name"] for cs in src.search_callsets(conf.variant_set_id)]
rows = [
    block["has_variation"]
    for contig in conf.get_contigs(src, conf.variant_set_id)
    for block in src.genotype_blocks(
        conf.variant_set_id[0], contig, block_size=conf.block_size,
        min_allele_frequency=conf.min_allele_frequency)
]
oracle = grm_reference(np.concatenate(rows), len(names))
expected = ["\t".join(["name", *names])] + [
    "\t".join(str(field) for field in row)
    for row in format_grm_rows(names, oracle)
]
actual = open(f"{tmp}/kin.tsv").read().splitlines()
if actual != expected:
    print("GRM kinship TSV differs from the NumPy oracle")
    sys.exit(1)
doc = read_manifest(f"{tmp}/grm.json")
errors = validate_manifest(doc)
if errors:
    print("GRM manifest INVALID:\n  " + "\n  ".join(errors)); sys.exit(1)
analysis = doc["analysis"]
if analysis["kind"] != "grm" or analysis["sites_tested"] != len(
        np.concatenate(rows)):
    print(f"GRM manifest analysis block wrong: {analysis}"); sys.exit(1)
print(f"GRM smoke OK: {analysis['sites_tested']} sites, kinship "
      "byte-identical to the NumPy oracle, manifest valid")
PYEOF
else
  echo "GRM smoke run failed (rc=$grm_rc):"; tail -10 "$AN_TMP/grm.err"
fi
[ "$grm_rc" -eq 0 ] || an_rc=1

# 2. LD prune on a 2-contig synthetic, twice: the kept-site mask must be
#    deterministic (byte-identical across runs) and match the windowed
#    NumPy oracle. Runs on its own step rc: a failure upstream must not
#    skip this coverage or masquerade as an LD failure.
ld_rc=0
ld_flags="--num-samples 8 --references 1:0:40000,2:0:40000 \
  --ld-r2-threshold 0.2 --ld-window-sites 64"
for run in a b; do
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    python -m spark_examples_tpu ld-prune $ld_flags \
      --ld-out "$AN_TMP/kept-$run.tsv" --metrics-json "$AN_TMP/ld-$run.json" \
      > /dev/null 2> "$AN_TMP/ld-$run.err" || ld_rc=$?
done
if [ "$ld_rc" -ne 0 ]; then
  echo "LD smoke run failed:"; tail -10 "$AN_TMP"/ld-*.err
elif ! cmp -s "$AN_TMP/kept-a.tsv" "$AN_TMP/kept-b.tsv"; then
  echo "LD kept-site mask is NOT deterministic across identical runs"
  ld_rc=1
else
  env JAX_PLATFORMS=cpu python - "$AN_TMP" $ld_flags <<'PYEOF' || ld_rc=$?
import sys
import numpy as np
from spark_examples_tpu.analyses.ld import ld_prune_reference
from spark_examples_tpu.config import LdConf
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
from spark_examples_tpu.pipeline.pca_driver import make_source

tmp, flags = sys.argv[1], sys.argv[2:]
conf = LdConf.parse(flags)
src = make_source(conf)
expected = ["contig\tpos\tkept"]
kept_total = tested_total = 0
for contig in conf.get_contigs(src, conf.variant_set_id):
    rows = [
        (block["positions"], block["has_variation"])
        for block in src.genotype_blocks(
            conf.variant_set_id[0], contig, block_size=conf.block_size,
            min_allele_frequency=conf.min_allele_frequency)
    ]
    positions = np.concatenate([p for p, _ in rows])
    hv = np.concatenate([h for _, h in rows])
    W = conf.ld_window_sites
    windows = [
        (positions[i:i + W], hv[i:i + W])
        for i in range(0, len(positions), W)
    ]
    for pos, kept in ld_prune_reference(
            windows, conf.num_samples, conf.ld_r2_threshold):
        expected.append(f"{contig.reference_name}\t{pos}\t{int(kept)}")
        kept_total += int(kept)
        tested_total += 1
actual = open(f"{tmp}/kept-a.tsv").read().splitlines()
if actual != expected:
    print("LD kept mask differs from the windowed NumPy oracle")
    sys.exit(1)
doc = read_manifest(f"{tmp}/ld-a.json")
errors = validate_manifest(doc)
if errors:
    print("LD manifest INVALID:\n  " + "\n  ".join(errors)); sys.exit(1)
analysis = doc["analysis"]
if analysis != {"kind": "ld", "sites_kept": kept_total,
                "sites_tested": tested_total}:
    print(f"LD manifest analysis block wrong: {analysis} vs "
          f"kept={kept_total} tested={tested_total}")
    sys.exit(1)
print(f"LD smoke OK: deterministic kept mask ({kept_total}/{tested_total} "
      "sites), oracle-exact, manifest valid")
PYEOF
fi
[ "$ld_rc" -eq 0 ] || an_rc=1

# 3. Association scan with a PLANTED signal: phenotypes are the carrier
#    vector of one polymorphic site, so that site's chi-square is the
#    theoretical maximum (n) and must rank top. Own step rc, like LD.
assoc_rc=0
env JAX_PLATFORMS=cpu python - "$AN_TMP" $an_flags <<'PYEOF' > /dev/null || assoc_rc=$?
import sys
import numpy as np
from spark_examples_tpu.config import AssocConf
from spark_examples_tpu.pipeline.pca_driver import make_source

tmp, flags = sys.argv[1], sys.argv[2:]
conf = AssocConf.parse(flags + ["--phenotypes", "unused"])
src = make_source(conf)
names = [cs["name"] for cs in src.search_callsets(conf.variant_set_id)]
for contig in conf.get_contigs(src, conf.variant_set_id):
    for block in src.genotype_blocks(
            conf.variant_set_id[0], contig, block_size=conf.block_size,
            min_allele_frequency=conf.min_allele_frequency):
        carriers = block["has_variation"].sum(axis=1)
        target = np.nonzero(
            (carriers >= 2) & (carriers <= len(names) - 2))[0]
        if len(target):
            i = int(target[0])
            with open(f"{tmp}/pheno.tsv", "w") as f:
                for name, status in zip(names, block["has_variation"][i]):
                    f.write(f"{name}\t{int(status)}\n")
            with open(f"{tmp}/signal.txt", "w") as f:
                f.write(
                    f"{contig.reference_name}\t{int(block['positions'][i])}"
                )
            sys.exit(0)
print("no polymorphic site found for the planted signal")
sys.exit(1)
PYEOF
if [ "$assoc_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    python -m spark_examples_tpu assoc-scan $an_flags \
      --phenotypes "$AN_TMP/pheno.tsv" --assoc-out "$AN_TMP/scan.tsv" \
      --metrics-json "$AN_TMP/assoc.json" \
      > "$AN_TMP/assoc.out" 2> "$AN_TMP/assoc.err" || assoc_rc=$?
fi
if [ "$assoc_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$AN_TMP" <<'PYEOF' || assoc_rc=$?
import sys
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest

tmp = sys.argv[1]
signal_contig, signal_pos = open(f"{tmp}/signal.txt").read().split()
best = None
with open(f"{tmp}/scan.tsv") as f:
    next(f)  # header
    for line in f:
        contig, pos, a, t, chi2 = line.rstrip("\n").split("\t")
        if best is None or float(chi2) > best[2]:
            best = (contig, pos, float(chi2))
if best is None or best[0] != signal_contig or best[1] != signal_pos:
    print(f"planted signal {signal_contig}:{signal_pos} NOT top-ranked "
          f"(top was {best})")
    sys.exit(1)
doc = read_manifest(f"{tmp}/assoc.json")
errors = validate_manifest(doc)
if errors:
    print("assoc manifest INVALID:\n  " + "\n  ".join(errors)); sys.exit(1)
if doc["analysis"]["kind"] != "assoc" or \
        doc["analysis"]["sites_tested"] <= 0:
    print(f"assoc manifest analysis block wrong: {doc['analysis']}")
    sys.exit(1)
print(f"assoc smoke OK: planted signal {signal_contig}:{signal_pos} "
      f"top-ranked (chi2 {best[2]:g}), manifest valid")
PYEOF
else
  echo "assoc smoke failed:"; tail -10 "$AN_TMP/assoc.err" 2>/dev/null
fi
[ "$assoc_rc" -eq 0 ] || an_rc=1
rm -rf "$AN_TMP"

echo "== serve smoke (resident daemon: admit, reject, warm cache, drain) =="
serve_rc=0
SERVE_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu serve --port 0 \
    --run-dir "$SERVE_TMP/run" --endpoint-file "$SERVE_TMP/endpoint" \
    > "$SERVE_TMP/daemon.out" 2> "$SERVE_TMP/daemon.err" &
SERVE_PID=$!
for _ in $(seq 1 150); do [ -f "$SERVE_TMP/endpoint" ] && break; sleep 0.2; done
if [ ! -f "$SERVE_TMP/endpoint" ]; then
  echo "serve smoke: daemon never published its endpoint"; serve_rc=1
  kill "$SERVE_PID" 2>/dev/null
  wait "$SERVE_PID" 2>/dev/null
else
  env JAX_PLATFORMS=cpu python - "$(cat "$SERVE_TMP/endpoint")" "$SERVE_PID" <<'PYEOF' || serve_rc=$?
import os, signal, sys, time, urllib.error
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
from spark_examples_tpu.obs.metrics import COMPILE_CACHE_GEOMETRY_HITS
from spark_examples_tpu.serve.client import ServeClient, ServeError

url, daemon_pid = sys.argv[1], int(sys.argv[2])
client = ServeClient(url)
flags = ["--num-samples", "8", "--references", "1:0:50000"]

# 1. plan-invalid request -> structured 400 carrying the plan finding.
try:
    client.submit(flags + ["--num-pc", "99"])
    print("plan-invalid submit was ACCEPTED"); sys.exit(1)
except ServeError as e:
    codes = [i["code"] for i in e.body.get("plan", {}).get("issues", [])]
    if e.status != 400 or e.code != "plan-rejected" \
            or "num-pc-exceeds-cohort" not in codes:
        print(f"rejection not structured: {e.status} {e.code} {codes}")
        sys.exit(1)

# 2. accepted synthetic job -> done, valid per-job schema-v2 manifest.
job = client.wait(client.submit(flags)["job"]["id"], timeout=300)["job"]
if job["status"] != "done" or job["compile_cache"] != "cold":
    print(f"first job not a clean cold run: {job['status']} "
          f"{job['compile_cache']} {job.get('error')}"); sys.exit(1)
errors = validate_manifest(read_manifest(job["manifest_path"]))
if errors:
    print("per-job manifest INVALID:\n  " + "\n  ".join(errors)); sys.exit(1)

# 3. identical resubmit -> warm compile-cache hit, visible in /metrics.
job2 = client.wait(client.submit(flags)["job"]["id"], timeout=300)["job"]
if job2["status"] != "done" or job2["compile_cache"] != "warm":
    print(f"identical resubmit not warm: {job2['status']} "
          f"{job2['compile_cache']}"); sys.exit(1)
hits = [l for l in client.metrics().splitlines()
        if l.startswith(COMPILE_CACHE_GEOMETRY_HITS + " ")]
if not hits or float(hits[0].split()[1]) < 1:
    print(f"/metrics shows no warm-geometry hit: {hits}"); sys.exit(1)

# 4. deadline below the calibrated estimate -> structured 413 carrying
#    both numbers; a feasible resubmit completes and its per-job manifest
#    lands the predicted-vs-measured cost block.
try:
    client.submit(flags, deadline_seconds=0.001)
    print("infeasible-deadline submit was ACCEPTED"); sys.exit(1)
except ServeError as e:
    if e.status != 413 or e.code != "deadline-infeasible":
        print(f"infeasible deadline not a structured 413: "
              f"{e.status} {e.code}"); sys.exit(1)
    cost = e.body.get("cost") or {}
    predicted = cost.get("predicted_seconds")
    message = (e.body.get("error") or {}).get("message") or ""
    if not predicted or cost.get("requested_deadline_seconds") != 0.001 \
            or "0.001" not in message or f"{predicted:.4g}" not in message:
        print(f"413 body does not name predicted vs requested: {e.body}")
        sys.exit(1)
job3 = client.wait(client.submit(flags)["job"]["id"], timeout=300)["job"]
cost_doc = read_manifest(job3["manifest_path"]).get("cost")
if not cost_doc or cost_doc.get("compile") not in ("warm", "cold") \
        or not isinstance(cost_doc.get("measured_seconds"), (int, float)) \
        or not isinstance(cost_doc.get("predicted_seconds"), (int, float)) \
        or not isinstance(cost_doc.get("queue_wait_seconds"), (int, float)):
    print(f"done job's manifest has no cost block: {cost_doc}"); sys.exit(1)

# 5. SIGTERM drain: a fresh-geometry job holds the worker (cold compile),
#    new submissions get 503, the in-flight job still finishes.
inflight = client.submit(["--num-samples", "12",
                          "--references", "1:0:50000"])["job"]
os.kill(daemon_pid, signal.SIGTERM)
drain_seen = False
for _ in range(20):
    try:
        client.submit(flags)
        time.sleep(0.05)
    except ServeError as e:
        if e.status == 503 and e.code == "draining":
            drain_seen = True
        break
    except urllib.error.URLError:
        break
if not drain_seen:
    print("drain window never returned 503 draining"); sys.exit(1)
manifest = os.path.join(os.path.dirname(os.path.dirname(
    job["manifest_path"])), inflight["id"], "manifest.json")
for _ in range(300):
    if os.path.exists(manifest):
        break
    time.sleep(0.2)
else:
    print(f"in-flight job never finished its manifest: {manifest}")
    sys.exit(1)
print(f"serve smoke OK: structured rejection, cold {job['seconds']:.2f}s "
      f"-> warm {job2['seconds']:.2f}s, per-job manifests valid, "
      "drain returned 503 and finished the in-flight job")
PYEOF
  kill -TERM "$SERVE_PID" 2>/dev/null
  if wait "$SERVE_PID"; then
    echo "serve smoke: daemon drained cleanly (exit 0)"
  else
    echo "serve smoke: daemon exited nonzero"; serve_rc=1
  fi
fi
if [ "$serve_rc" -ne 0 ]; then
  echo "serve smoke failed (rc=$serve_rc):"; tail -20 "$SERVE_TMP/daemon.err"
fi
rm -rf "$SERVE_TMP"

echo "== serve concurrency smoke (slices, journal replay, warm restart, load) =="
sc_rc=0
SC_TMP=$(mktemp -d)
sc_daemon() {
  rm -f "$SC_TMP/endpoint"
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
      XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m spark_examples_tpu serve --port 0 \
      --run-dir "$SC_TMP/run" --endpoint-file "$SC_TMP/endpoint" \
      --executor-slices 1 --serve-small-site-limit 5000 \
      >> "$SC_TMP/daemon.out" 2>> "$SC_TMP/daemon.err" &
  SC_PID=$!
  for _ in $(seq 1 150); do [ -f "$SC_TMP/endpoint" ] && break; sleep 0.2; done
  [ -f "$SC_TMP/endpoint" ]
}
if ! sc_daemon; then
  echo "serve concurrency smoke: daemon never published its endpoint"; sc_rc=1
  kill "$SC_PID" 2>/dev/null; wait "$SC_PID" 2>/dev/null
else
  # Phase 1: a large job in flight must NOT head-block a small job — the
  # small job (via the `submit --wait` verb, Retry-After-paced) completes
  # on its own slice while the large job is still on the devices. Then
  # queue a second large job behind the first and SIGKILL the daemon
  # mid-queue (the journal's moment of truth).
  env JAX_PLATFORMS=cpu python - "$(cat "$SC_TMP/endpoint")" "$SC_TMP" <<'PYEOF' || sc_rc=$?
import subprocess, sys, time
from spark_examples_tpu.serve.client import ServeClient

url, tmp = sys.argv[1], sys.argv[2]
client = ServeClient(url)
SMALL = ["--num-samples", "8", "--references", "1:0:50000"]
LARGE = ["--num-samples", "512", "--references", "1:0:20000000"]

# Warm the small geometry (its compile is the daemon's startup cost).
first = client.wait(client.submit(SMALL)["job"]["id"], timeout=300)["job"]
if first["status"] != "done" or first["slice"] != "small-0":
    print(f"small job not served by the small slice: {first}"); sys.exit(1)

large1 = client.submit(LARGE)["job"]
if large1["class"] != "large":
    print(f"large job misclassified: {large1}"); sys.exit(1)
t0 = time.perf_counter()
wait = subprocess.run(
    [sys.executable, "-m", "spark_examples_tpu", "submit", "--url", url,
     "--wait", "--json", "--"] + SMALL,
    capture_output=True, text=True, timeout=300)
small_seconds = time.perf_counter() - t0
if wait.returncode != 0:
    print(f"submit --wait failed: {wait.stdout}\n{wait.stderr}"); sys.exit(1)
inflight = client.status(large1["id"])["job"]
if inflight["status"] not in ("queued", "running"):
    print(f"large job already {inflight['status']} after "
          f"{small_seconds:.2f}s small job: no concurrency"); sys.exit(1)
large1_done = client.wait(large1["id"], timeout=600)["job"]
if large1_done["status"] != "done":
    print(f"large job failed: {large1_done}"); sys.exit(1)

# Mid-queue kill setup: large2 running, large3 queued behind it.
large2 = client.submit(LARGE)["job"]
deadline = time.monotonic() + 60
while client.status(large2["id"])["job"]["status"] == "queued":
    if time.monotonic() > deadline:
        print("large2 never started"); sys.exit(1)
    time.sleep(0.1)
large3 = client.submit(LARGE)["job"]
with open(tmp + "/ids", "w") as f:
    f.write(f"{large2['id']}\n{large3['id']}\n")
print(f"serve concurrency phase 1 OK: small {small_seconds:.2f}s beside "
      f"large ({large1_done['seconds']:.2f}s), large2 running + "
      "large3 queued for the kill")
PYEOF
  if [ "$sc_rc" -eq 0 ]; then
    kill -9 "$SC_PID" 2>/dev/null
    wait "$SC_PID" 2>/dev/null
    # Phase 2: the restarted daemon must replay the journal — the queued
    # job finishes, the mid-device job fails structurally, and a
    # repeat-geometry job is warm from the run-dir persistent state.
    if ! sc_daemon; then
      echo "serve concurrency smoke: daemon did not restart"; sc_rc=1
    else
      env JAX_PLATFORMS=cpu python - "$(cat "$SC_TMP/endpoint")" "$SC_TMP" <<'PYEOF' || sc_rc=$?
import sys
from spark_examples_tpu.serve.client import ServeClient

url, tmp = sys.argv[1], sys.argv[2]
client = ServeClient(url)
large2_id, large3_id = open(tmp + "/ids").read().split()

health = client.healthz()
if health["warm_state"]["journal_replayed"] < 2:
    print(f"journal replayed too few jobs: {health['warm_state']}")
    sys.exit(1)
crashed = client.wait(large2_id, timeout=60)["job"]
if crashed["status"] != "failed" or "daemon-restarted" not in (crashed["error"] or ""):
    print(f"mid-device job not failed structurally: {crashed}"); sys.exit(1)
replayed = client.wait(large3_id, timeout=600)["job"]
if replayed["status"] != "done":
    print(f"journaled queued job did not finish after restart: {replayed}")
    sys.exit(1)
SMALL = ["--num-samples", "8", "--references", "1:0:50000"]
repeat = client.wait(client.submit(SMALL)["job"]["id"], timeout=300)["job"]
if repeat["compile_cache"] != "warm":
    print(f"repeat-geometry job not warm after restart: {repeat}")
    sys.exit(1)
# The calibration ledger is append-only and fsync'd: the kill -9 above
# must not have cost the pre-kill measured samples. The restarted daemon
# alone completed only 2 jobs (large3 + repeat; large2 failed, failures
# are never recorded) — more than 2 folded samples proves the pre-kill
# rows survived the crash.
from spark_examples_tpu.obs.calibration import calibration_path, fold_calibration
fold = fold_calibration(calibration_path(tmp + "/run"))
if fold.overall.n <= 2:
    print(f"calibration ledger lost pre-kill samples: n={fold.overall.n}")
    sys.exit(1)
print(f"serve concurrency phase 2 OK: {health['warm_state']['journal_replayed']} "
      f"jobs replayed, queued job finished ({replayed['seconds']:.2f}s), "
      "mid-device job failed structurally, repeat geometry warm from the "
      f"persistent run-dir state, calibration ledger kept {fold.overall.n} "
      "samples across kill -9")
PYEOF
      kill -TERM "$SC_PID" 2>/dev/null
      if ! wait "$SC_PID"; then
        echo "serve concurrency smoke: restarted daemon exited nonzero"; sc_rc=1
      fi
    fi
  else
    kill -9 "$SC_PID" 2>/dev/null; wait "$SC_PID" 2>/dev/null
  fi
fi
if [ "$sc_rc" -eq 0 ]; then
  # Phase 3: the serve-load harness — mixed small/large traffic through
  # the HTTP API; small-job P99 under concurrent large-job load must stay
  # within ~2x its unloaded P99 (a 2 s absolute floor absorbs shared-CI
  # scheduler noise on a 2-core container) and far below the large job's
  # own wall-clock (the head-block detector).
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
      XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python bench.py --config serve-load > "$SC_TMP/load.json" \
      2> "$SC_TMP/load.err" || sc_rc=$?
  if [ "$sc_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$SC_TMP/load.json" <<'PYEOF' || sc_rc=$?
import json, sys
doc = json.load(open(sys.argv[1]))
d = doc["details"]
if not d["sliced"]:
    print(f"serve-load ran unsliced: {d['slices']}"); sys.exit(1)
unloaded = d["small_unloaded_seconds"]["p99"]
loaded = d["small_loaded_seconds"]["p99"]
large = d["large_job_seconds"]
if loaded > max(2.0 * unloaded, unloaded + 2.0):
    print(f"small-job P99 degraded past 2x under load: "
          f"{loaded:.3f}s vs {unloaded:.3f}s unloaded"); sys.exit(1)
if loaded >= large:
    print(f"small-job P99 {loaded:.3f}s >= large job {large:.3f}s: "
          "head-of-line blocking"); sys.exit(1)
# The /v1/fleet/stats document the bench fetched over HTTP must be
# valid and carry nonzero small-class quantiles + a calibration fold.
fs = d["fleet_stats"]
wall = ((fs.get("classes") or {}).get("small") or {}).get("wall_seconds") or {}
if not wall.get("count") or not wall.get("p99") or wall["p99"] <= 0:
    print(f"/v1/fleet/stats has no nonzero small wall quantiles: {fs}")
    sys.exit(1)
if not (fs.get("calibration") or {}).get("samples"):
    print(f"/v1/fleet/stats calibration fold empty: {fs}"); sys.exit(1)
# Fused-batch phase: the one-program group must be byte-identical to
# the same jobs back to back and at least 2x their group throughput
# (the acceptance bound; BENCH_r07 records ~5.8x on this host).
fb = d["fused_batch"]
if not fb["byte_identical"]:
    print("fused-batch phase lost byte parity"); sys.exit(1)
if fb["fused"]["dispatch"]["fused_groups"] < 1 \
        or fb["serial"]["dispatch"]["fused_groups"] != 0:
    print(f"fused-batch dispatch counters wrong: fused ran "
          f"{fb['fused']['dispatch']}, serial ran {fb['serial']['dispatch']}")
    sys.exit(1)
ratio = fb["group_throughput_ratio"]
if not ratio or ratio < 2.0:
    print(f"fused group throughput below the 2x bound: {ratio}")
    sys.exit(1)
# Cost-ordered scheduling: cheap jobs queued behind an expensive one
# must finish ahead of it (SJF within the class lane) and cut the
# cheap-job P99 relative to FIFO on the identical load.
co = d["cost_ordering"]
if co["cost"]["cheap_p99_seconds"] >= co["cost"]["expensive_latency_seconds"]:
    print(f"cost ordering left cheap jobs behind the expensive one: "
          f"{co['cost']}"); sys.exit(1)
if not co["fifo_over_cost_p99"] or co["fifo_over_cost_p99"] <= 1.0:
    print(f"cost ordering did not beat FIFO: {co}"); sys.exit(1)
print(f"serve-load OK: small P99 {unloaded:.3f}s unloaded -> "
      f"{loaded:.3f}s beside a {large:.2f}s large job "
      f"({doc['value']}x, bound 2x); fleet stats: small wall p99 "
      f"{wall['p99']:.3f}s over {wall['count']} jobs, calibration "
      f"n={fs['calibration']['samples']}; fused group {ratio:.1f}x "
      f"serial (byte-identical), cost ordering cut cheap P99 "
      f"{co['fifo_over_cost_p99']:.2f}x vs FIFO")
PYEOF
  else
    echo "serve-load bench failed:"; tail -10 "$SC_TMP/load.err"
  fi
fi
if [ "$sc_rc" -ne 0 ]; then
  echo "serve concurrency smoke failed (rc=$sc_rc):"
  tail -20 "$SC_TMP/daemon.err" 2>/dev/null
fi
rm -rf "$SC_TMP"

echo "== fused batch + cost-ordering smoke (one device program per group) =="
fb_rc=0
FB_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
  python -m spark_examples_tpu serve --port 0 \
    --run-dir "$FB_TMP/run" --endpoint-file "$FB_TMP/endpoint" \
    --executor-slices 0 --batch-max-jobs 3 --batch-linger-seconds 2.0 \
    --serve-small-site-limit 500000 \
    > "$FB_TMP/daemon.out" 2> "$FB_TMP/daemon.err" &
FB_PID=$!
for _ in $(seq 1 150); do [ -f "$FB_TMP/endpoint" ] && break; sleep 0.2; done
if [ ! -f "$FB_TMP/endpoint" ]; then
  echo "fused smoke: daemon never published its endpoint"; fb_rc=1
  kill "$FB_PID" 2>/dev/null; wait "$FB_PID" 2>/dev/null
else
  env JAX_PLATFORMS=cpu python - "$(cat "$FB_TMP/endpoint")" <<'PYEOF' || fb_rc=$?
import json, sys, urllib.request
from spark_examples_tpu.serve.client import ServeClient, ServeError

url = sys.argv[1]
client = ServeClient(url)
SMALL = ["--num-samples", "8", "--references", "1:0:50000"]

# 1. Three identical small jobs land inside the linger window -> the
#    daemon runs the group as ONE stacked device program and every
#    member envelope records the group size it rode in.
ids = [client.submit(SMALL)["job"]["id"] for _ in range(3)]
fused = [client.wait(j, timeout=600)["job"] for j in ids]
for job in fused:
    if job["status"] != "done" or job["fused_size"] != 3:
        print(f"group member not fused: {job['status']} "
              f"fused_size={job['fused_size']} {job.get('error')}")
        sys.exit(1)

# 2. Serial resubmits of the SAME geometry (one at a time — a
#    singleton batch never fuses) must be byte-identical to the fused
#    group's results.
serial = [client.wait(client.submit(SMALL)["job"]["id"], timeout=600)["job"]
          for _ in range(2)]
reference = serial[0]["result"]["pc_lines"]
for job in serial[1:] + fused:
    if job["result"]["pc_lines"] != reference:
        print("fused group results diverged from serial resubmits")
        sys.exit(1)
for job in serial:
    if job["fused_size"] != 1:
        print(f"singleton batch fused anyway: {job['fused_size']}")
        sys.exit(1)

# 3. /v1/fleet/stats partitions every executed job fused vs serial.
with urllib.request.urlopen(url + "/v1/fleet/stats", timeout=30) as resp:
    dispatch = json.loads(resp.read().decode("utf-8"))["dispatch"]
if dispatch["fused_groups"] < 1 or dispatch["fused_jobs"] < 3 \
        or dispatch["serial_jobs"] < 2:
    print(f"dispatch counters wrong: {dispatch}"); sys.exit(1)

# 4. An over-HBM fused group is a structured 413 at admission: the
#    plan charges K stacked accumulators against the HBM budget
#    device-free and names the cohort's fused-group ceiling.
try:
    client.submit(["--num-samples", "20000", "--references", "1:0:50000",
                   "--pca-backend", "tpu", "--fused-jobs", "12"])
    print("over-HBM fused group was ACCEPTED"); sys.exit(1)
except ServeError as e:
    codes = [i["code"] for i in e.body.get("plan", {}).get("issues", [])]
    if e.status != 413 or e.code != "plan-rejected" \
            or "fused-group-exceeds-hbm" not in codes:
        print(f"over-HBM group not a structured 413: "
              f"{e.status} {e.code} {codes}")
        sys.exit(1)
    ceiling = e.body["plan"]["geometry"].get("max_fused_jobs")
    if not ceiling or ceiling >= 12:
        print(f"413 geometry does not carry a real fused ceiling: {ceiling}")
        sys.exit(1)

# 5. Cost ordering: a cheap job admitted BEHIND an expensive one
#    completes first. The blocker's geometry differs from the
#    expensive job's so they can never coalesce into one group.
BLOCKER = ["--num-samples", "144", "--references", "1:0:10000000"]
EXPENSIVE = ["--num-samples", "128", "--references", "1:0:10000000"]
blocker = client.submit(BLOCKER)["job"]["id"]
expensive = client.submit(EXPENSIVE)["job"]["id"]
cheap = client.submit(SMALL)["job"]["id"]
cheap_done = client.wait(cheap, timeout=600)["job"]
expensive_done = client.wait(expensive, timeout=600)["job"]
client.wait(blocker, timeout=600)
if cheap_done["status"] != "done" or expensive_done["status"] != "done":
    print(f"ordering smoke jobs failed: {cheap_done.get('error')} "
          f"{expensive_done.get('error')}"); sys.exit(1)
if cheap_done["finished_unix"] >= expensive_done["finished_unix"]:
    print(f"cheap job did not overtake the expensive one: cheap finished "
          f"at +{cheap_done['finished_unix'] - expensive_done['finished_unix']:.3f}s")
    sys.exit(1)
print(f"fused smoke OK: 3-job group one device program (byte-identical "
      f"to serial resubmits), dispatch {dispatch['fused_groups']} fused "
      f"group(s) / {dispatch['serial_jobs']} serial, over-HBM group 413 "
      f"(ceiling {ceiling}), cheap job overtook the expensive one by "
      f"{expensive_done['finished_unix'] - cheap_done['finished_unix']:.2f}s")
PYEOF
  kill -TERM "$FB_PID" 2>/dev/null
  if wait "$FB_PID"; then
    echo "fused smoke: daemon drained cleanly (exit 0)"
  else
    echo "fused smoke: daemon exited nonzero"; fb_rc=1
  fi
fi
if [ "$fb_rc" -ne 0 ]; then
  echo "fused batch smoke failed (rc=$fb_rc):"; tail -20 "$FB_TMP/daemon.err"
fi
rm -rf "$FB_TMP"

echo "== multi-replica serving smoke (lease-fenced work stealing) =="
rep_rc=0
REP_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    SPARK_EXAMPLES_TPU_FAULTS='kill@serve.worker.mid-job' \
  python -m spark_examples_tpu serve --port 0 \
    --run-dir "$REP_TMP/rd" --replica-id a --executor-slices 0 \
    --no-persistent-cache --lease-seconds 1.0 --lease-grace-seconds 0.2 \
    --steal-interval-seconds 0.2 \
    --endpoint-file "$REP_TMP/endpoint.a" 2> "$REP_TMP/daemon.a.err" &
REP_A_PID=$!
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu serve --port 0 \
    --run-dir "$REP_TMP/rd" --replica-id b --executor-slices 0 \
    --no-persistent-cache --lease-seconds 1.0 --lease-grace-seconds 0.2 \
    --steal-interval-seconds 0.2 \
    --endpoint-file "$REP_TMP/endpoint.b" 2> "$REP_TMP/daemon.b.err" &
REP_B_PID=$!
for _ in $(seq 1 600); do
  [ -f "$REP_TMP/endpoint.a" ] && [ -f "$REP_TMP/endpoint.b" ] && break
  sleep 0.1
done
if [ ! -f "$REP_TMP/endpoint.a" ] || [ ! -f "$REP_TMP/endpoint.b" ]; then
  echo "replica smoke: a replica never published its endpoint"; rep_rc=1
else
  env JAX_PLATFORMS=cpu python - \
      "$(cat "$REP_TMP/endpoint.a")" "$(cat "$REP_TMP/endpoint.b")" \
      "$REP_A_PID" <<'PYEOF' || rep_rc=$?
import sys, time
from spark_examples_tpu.serve.client import ServeClient, ServeError

a_url, b_url, a_pid = sys.argv[1], sys.argv[2], int(sys.argv[3])
small = ["--num-samples", "8", "--references", "1:0:50000"]
large = ["--num-samples", "8", "--references", "1:0:30000000"]

# The large job lands on replica a, whose fault plan SIGKILLs it the
# moment device work begins — the owning replica dies mid-device.
job_id = ServeClient(a_url, timeout=60).submit(large)["job"]["id"]
assert job_id.startswith("job-a-"), job_id

# Small jobs keep flowing through the survivor THROUGHOUT the failover.
b = ServeClient(b_url, timeout=60, max_retries=5)
small_done = 0
stolen = None
deadline = time.monotonic() + 240
while time.monotonic() < deadline:
    doc = b.wait(b.submit(small)["job"]["id"], timeout=120)
    assert doc["job"]["status"] == "done", doc
    small_done += 1
    try:
        status = b.status(job_id)["job"]
    except ServeError as e:
        if e.status != 404:
            raise
        continue  # not stolen yet
    if status["status"] in ("done", "failed", "cancelled"):
        stolen = status
        if small_done >= 3:
            break
if stolen is None:
    raise SystemExit(f"survivor never settled the orphaned job "
                     f"({small_done} small jobs served meanwhile)")
# device_began was journaled before the kill: the survivor must fail it
# structurally, never silently re-run the devices.
if stolen["status"] != "failed" or \
        not (stolen["error"] or "").startswith("replica-failover:"):
    raise SystemExit(f"stolen mid-device job not failed structurally: "
                     f"{stolen}")
health = b.healthz()
rep = health["replica"]
if rep["jobs_stolen"] < 1:
    raise SystemExit(f"survivor reports no stolen jobs: {rep}")
# The client endpoint list fails over off the dead replica.
failover = ServeClient(f"{a_url},{b_url}", timeout=60, max_retries=5)
via = failover.status(job_id)["job"]
assert via["status"] == "failed", via
print(f"replica smoke OK: owner SIGKILLed mid-device, survivor stole "
      f"the job under epoch fencing -> {stolen['error'][:40]}..., "
      f"{small_done} small jobs flowed throughout, client failed over "
      f"({rep['jobs_stolen']} stolen, {rep['alive']} alive)")
PYEOF
fi
kill -TERM "$REP_B_PID" 2>/dev/null
wait "$REP_B_PID" 2>/dev/null
wait "$REP_A_PID" 2>/dev/null
if [ "$rep_rc" -eq 0 ]; then
  # Flight-recorder trace export: the two-replica chaos run above (owner
  # SIGKILLed mid-device, survivor stole under epoch fencing) must merge
  # into ONE well-formed Chrome trace — the stolen job's span tree
  # complete across both replicas, the steal flow arrow whole, epochs
  # and the fenced terminal state present, zero orphan spans.
  env JAX_PLATFORMS=cpu python -m spark_examples_tpu trace export \
    --run-dir "$REP_TMP/rd" --out "$REP_TMP/fleet.trace.json" || rep_rc=$?
  if [ "$rep_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$REP_TMP/fleet.trace.json" <<'PYEOF' || rep_rc=$?
import json, sys
from spark_examples_tpu.obs.trace import validate_chrome_trace

doc = json.load(open(sys.argv[1]))
errors = validate_chrome_trace(doc)
if errors:
    print("merged trace NOT well-formed:\n  " + "\n  ".join(errors))
    sys.exit(1)
jobs = doc["otherData"]["jobs"]
stolen = {j: f for j, f in jobs.items() if f.get("stolen")}
if not stolen:
    print(f"merged trace records no stolen job: {list(jobs)}")
    sys.exit(1)
job_id, facts = sorted(stolen.items())[0]
if facts["status"] != "failed":
    print(f"stolen job's fenced terminal state wrong: {facts}")
    sys.exit(1)
if facts["lease_epoch"] < 2 or not facts.get("trace"):
    print(f"stolen job missing fencing epoch or trace id: {facts}")
    sys.exit(1)
events = doc["traceEvents"]
job_events = [e for e in events
              if (e.get("args") or {}).get("job") == job_id]
pids = {e["pid"] for e in job_events}
if len(pids) < 2:
    print(f"stolen job's span tree does not cross both replicas: "
          f"pids={pids}")
    sys.exit(1)
traces = {(e.get("args") or {}).get("trace") for e in job_events}
if traces - {facts["trace"]}:
    print(f"stolen job's events carry mixed trace ids: {traces}")
    sys.exit(1)
spans = [e for e in job_events if e["ph"] == "X" and e["name"] == "job"]
if not any(s["args"].get("truncated") for s in spans):
    print("the killed owner's job span was not closed as truncated: "
          f"{spans}")
    sys.exit(1)
if not any(s["args"].get("epoch") for s in spans):
    print(f"job spans carry no lease epoch: {spans}")
    sys.exit(1)
arrows = [e for e in events
          if e["ph"] in ("s", "f") and e["name"] == f"steal {job_id}"]
if {e["ph"] for e in arrows} != {"s", "f"}:
    print(f"stolen job has no whole steal flow arrow: {arrows}")
    sys.exit(1)
terminals = [e for e in job_events if e["name"] == "terminal"
             and e["args"].get("status") == "failed"]
if not terminals:
    print("survivor's terminal event for the stolen job is missing")
    sys.exit(1)
print(f"trace export OK: {doc['otherData']['recorder_events']} events, "
      f"{len(doc['otherData']['replicas'])} replicas, stolen job "
      f"{job_id} complete across {len(pids)} processes (steal arrow + "
      f"epoch {facts['lease_epoch']} + fenced terminal "
      f"'{facts['status']}'), zero orphan spans")
PYEOF
  fi
fi
if [ "$rep_rc" -eq 0 ]; then
  # Post-mortem cost observatory: with the whole fleet dead, `obs
  # report` must reconstruct the stolen job's prediction, wall, and
  # queue-wait under its one trace id — purely from the run-dir
  # artifacts (journal + calibration ledger + recorder segments).
  env JAX_PLATFORMS=cpu python -m spark_examples_tpu obs report \
    --run-dir "$REP_TMP/rd" --json > "$REP_TMP/fleet.report.json" \
    || rep_rc=$?
  if [ "$rep_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$REP_TMP/fleet.report.json" <<'PYEOF' || rep_rc=$?
import json, sys
doc = json.load(open(sys.argv[1]))
stolen = {j: f for j, f in doc["jobs"].items() if f.get("stolen")}
if not stolen:
    print(f"fleet report records no stolen job: {list(doc['jobs'])}")
    sys.exit(1)
job_id, facts = sorted(stolen.items())[0]
missing = [k for k in
           ("trace", "predicted_seconds", "measured_seconds",
            "queue_wait_seconds")
           if facts.get(k) is None]
if missing:
    print(f"fleet report's stolen job {job_id} lacks {missing}: {facts}")
    sys.exit(1)
if facts["status"] != "failed":
    print(f"stolen job's fenced status wrong in the report: {facts}")
    sys.exit(1)
if not doc["totals"]["ledger_samples"] or not doc["recorder"]:
    print(f"report missing ledger or recorder facts: {doc['totals']}")
    sys.exit(1)
print(f"obs report OK (fleet dead): stolen job {job_id} trace="
      f"{facts['trace'][:8]}... predicted {facts['predicted_seconds']:.2f}s,"
      f" wall {facts['measured_seconds']:.2f}s, queue wait "
      f"{facts['queue_wait_seconds']:.2f}s; "
      f"{doc['totals']['ledger_samples']} ledger samples, "
      f"{doc['recorder']['events']} recorder events")
PYEOF
  fi
fi
if [ "$rep_rc" -ne 0 ]; then
  echo "replica smoke failed (rc=$rep_rc):"
  tail -20 "$REP_TMP"/daemon.*.err 2>/dev/null
fi
rm -rf "$REP_TMP"
if [ "$rep_rc" -eq 0 ]; then
  # The lease substrate's locks must keep the acquisition graph acyclic.
  env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck lockgraph \
    || rep_rc=$?
fi
if [ "$rep_rc" -eq 0 ]; then
  # The full two-replica chaos matrix: SIGKILL at every registered serve
  # kill-point, survivor results byte-compared to a solo-replica oracle.
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    python -m pytest tests/test_serve_replicas_chaos.py -q \
      -p no:cacheprovider || rep_rc=$?
fi

echo "== faults stage (kill/resume parity + serve watchdog) =="
faults_rc=0
FAULTS_TMP=$(mktemp -d)
faults_flags="--num-samples 8 --references 1:0:150000 --ingest packed \
  --checkpoint-every-sites 40"
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu variants-pca $faults_flags \
    --gramian-checkpoint-dir "$FAULTS_TMP/ck-oracle" \
    --output-path "$FAULTS_TMP/oracle" \
    > /dev/null 2> "$FAULTS_TMP/oracle.err" || faults_rc=$?
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    SPARK_EXAMPLES_TPU_FAULTS='kill@checkpoint.post-save#2' \
  python -m spark_examples_tpu variants-pca $faults_flags \
    --gramian-checkpoint-dir "$FAULTS_TMP/ck" \
    --output-path "$FAULTS_TMP/killed" \
    > /dev/null 2> "$FAULTS_TMP/killed.err"
kill_rc=$?
if [ "$kill_rc" -ne 137 ]; then
  echo "faults smoke: killed run exited $kill_rc, expected 137 (SIGKILL)"
  faults_rc=1
fi
env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
  python -m spark_examples_tpu variants-pca $faults_flags \
    --gramian-checkpoint-dir "$FAULTS_TMP/ck" \
    --resume-from "$FAULTS_TMP/ck" \
    --output-path "$FAULTS_TMP/resumed" \
    --metrics-json "$FAULTS_TMP/resumed.json" \
    > /dev/null 2> "$FAULTS_TMP/resumed.err" || faults_rc=$?
if [ "$faults_rc" -eq 0 ]; then
  if ! cmp -s "$FAULTS_TMP/oracle-pca.tsv/part-00000" \
              "$FAULTS_TMP/resumed-pca.tsv/part-00000"; then
    echo "faults smoke: resumed eigenvectors DIFFER from the oracle"
    faults_rc=1
  fi
fi
if [ "$faults_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python - "$FAULTS_TMP/resumed.json" <<'PYEOF' || faults_rc=$?
import sys
from spark_examples_tpu.obs.manifest import read_manifest, validate_manifest
doc = read_manifest(sys.argv[1])
errors = validate_manifest(doc)
if errors:
    print("resumed manifest INVALID:\n  " + "\n  ".join(errors))
    sys.exit(1)
resume = doc.get("resume")
if not resume or resume["sites_skipped"] <= 0:
    print(f"resumed manifest carries no resume fast-forward: {resume}")
    sys.exit(1)
print(f"kill/resume smoke OK: SIGKILL at checkpoint.post-save#2, resumed "
      f"past {resume['sites_skipped']} sites, eigenvectors byte-identical")
PYEOF
else
  echo "faults smoke failed (rc=$faults_rc):"
  tail -5 "$FAULTS_TMP"/*.err 2>/dev/null
fi
if [ "$faults_rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu SPARK_EXAMPLES_TPU_NO_CACHE=1 \
    python - "$FAULTS_TMP" <<'PYEOF' || faults_rc=$?
import sys, time
from spark_examples_tpu.serve.daemon import PcaService
from spark_examples_tpu.serve.executor import ExecutionOutcome
from spark_examples_tpu.serve.protocol import request_doc
from spark_examples_tpu.utils import faults

calls = []
def executor(job, run_dir):
    calls.append(job.id)
    return ExecutionOutcome(result={"ok": True}, manifest_path=None,
                            compile_cache="cold")

def wait_terminal(svc, job_id, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _s, doc = svc.job_status(job_id)
        if doc["job"]["status"] in ("done", "failed", "cancelled"):
            return doc["job"]
        time.sleep(0.02)
    raise SystemExit(f"job {job_id} never reached a terminal state")

flags = ["--num-samples", "8", "--references", "1:0:50000"]
faults.configure("crash@serve.worker.mid-job")
svc = PcaService(run_dir=sys.argv[1] + "/serve", executor=executor).start()
_s, doc = svc.submit(request_doc(flags))
assert _s == 202, doc
crashed = wait_terminal(svc, doc["job"]["id"])
if crashed["status"] != "failed" or \
        not (crashed["error"] or "").startswith("worker-crashed:"):
    raise SystemExit(f"crashed job not failed structurally: {crashed}")
health = svc.healthz()
if health["status"] != "ok" or not health["queue"]["worker_alive"]:
    raise SystemExit(f"daemon unhealthy after worker crash: {health}")
_s, doc2 = svc.submit(request_doc(flags))
assert _s == 202, doc2
recovered = wait_terminal(svc, doc2["job"]["id"])
if recovered["status"] != "done":
    raise SystemExit(f"post-crash job did not complete: {recovered}")
if not svc.stop(timeout=10.0):
    raise SystemExit("daemon did not drain after recovery")
print(f"serve watchdog smoke OK: crash mid-job -> failed "
      f"({crashed['error'][:40]}...), {health['queue']['worker_restarts']} "
      "restart, next job done, clean drain")
PYEOF
fi
rm -rf "$FAULTS_TMP"

san_rc=0
if [ "$SANITIZE" = "1" ]; then
  echo "== sanitizer stage (graftcheck sanitize) =="
  env JAX_PLATFORMS=cpu python -m spark_examples_tpu graftcheck sanitize || san_rc=$?
fi

if [ "$rc" -ne 0 ]; then exit "$rc"; fi
if [ "$lint_rc" -ne 0 ]; then exit "$lint_rc"; fi
if [ "$proto_rc" -ne 0 ]; then exit "$proto_rc"; fi
if [ "$ir_rc" -ne 0 ]; then exit "$ir_rc"; fi
if [ "$rg_rc" -ne 0 ]; then exit "$rg_rc"; fi
if [ "$sched_rc" -ne 0 ]; then exit "$sched_rc"; fi
if [ "$mh_rc" -ne 0 ]; then exit "$mh_rc"; fi
if [ "$hm_rc" -ne 0 ]; then exit "$hm_rc"; fi
if [ "$obs_rc" -ne 0 ]; then exit "$obs_rc"; fi
if [ "$ring_rc" -ne 0 ]; then exit "$ring_rc"; fi
if [ "$an_rc" -ne 0 ]; then exit "$an_rc"; fi
if [ "$serve_rc" -ne 0 ]; then exit "$serve_rc"; fi
if [ "$sc_rc" -ne 0 ]; then exit "$sc_rc"; fi
if [ "$fb_rc" -ne 0 ]; then exit "$fb_rc"; fi
if [ "$rep_rc" -ne 0 ]; then exit "$rep_rc"; fi
if [ "$faults_rc" -ne 0 ]; then exit "$faults_rc"; fi
exit "$san_rc"

"""Benchmark: 1000 Genomes whole-genome PCoA on one TPU chip.

Baseline (BASELINE.md): the reference runs the whole-genome 1KG phase 1 PCoA
(2,504 samples, ~39.4M variant sites) in ~2 hours on 40 CPU cores
(``/root/reference/README.md:126-138``). North star: < 5 minutes on a v5e-8.

What this measures on the real chip:

1. Sustained Gramian throughput (variants/sec/chip): stream packed uint8
   genotype blocks host→device and accumulate ``G += XᵀX`` (bf16 MXU,
   f32 accumulation) in steady state, including the host→device transfer.
   Distinct synthetic blocks are cycled from a pre-generated working set so
   host-side synthesis (which stands in for the reference's API ingest) is
   not what's being measured.
2. The finalize path at full cohort size, after compile warmup: cross-device
   reduce + Gower centering + eigh of the 2504×2504 matrix + top-2 PCs.

Reported value: projected whole-genome wall-clock = 39.4M variants at the
measured sustained rate + measured finalize time. ``vs_baseline`` is the
speedup over the reference's 7200 s.

Prints exactly one JSON line.
"""

import json
import os
import time

import numpy as np

N_SAMPLES = 2504
WHOLE_GENOME_VARIANTS = 39_400_000  # 1KG phase 1, autosomes (README.md:126-138)
BASELINE_SECONDS = 7200.0
BLOCK = 2048
WORKING_SET_BLOCKS = 64
MIN_BENCH_SECONDS = 12.0


def main() -> None:
    import jax

    # Persistent compilation cache: eigh at (2504, 2504) costs minutes to
    # compile on first run, milliseconds after. Lives outside the repo so
    # cache binaries never enter git.
    cache_dir = os.path.join(
        os.path.expanduser("~/.cache"), "spark_examples_tpu", "jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from spark_examples_tpu.ops.centering import gower_center
    from spark_examples_tpu.ops.gramian import GramianAccumulator
    from spark_examples_tpu.ops.pca import principal_components_subspace
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    device = jax.devices()[0]

    # Working set of packed genotype blocks from the synthetic cohort.
    # Generated via the vectorized packed path; each block is ~2048 variant
    # rows of 2504 {0,1} entries (some rows short of BLOCK are zero-padded —
    # zero rows don't affect the Gramian).
    source = SyntheticGenomicsSource(num_samples=N_SAMPLES, seed=42)
    gen_start = time.perf_counter()
    positions = np.arange(0, WORKING_SET_BLOCKS * BLOCK * 100, 100, dtype=np.int64)
    blocks = []
    for b in range(WORKING_SET_BLOCKS):
        pos = positions[b * BLOCK : (b + 1) * BLOCK]
        alleles = source._genotype_alleles("bench-1kg", pos)
        blocks.append((alleles.max(axis=2) > 0).astype(np.uint8))
    gen_seconds = time.perf_counter() - gen_start

    # Warmup: compile the update path only. CRITICAL: no device→host fetch
    # before the measured loop — a single device_get permanently degrades
    # subsequent host→device dispatch ~50× on this remote-attached backend
    # (measured; the real pipeline is naturally safe because it fetches
    # nothing until the final result).
    acc = GramianAccumulator(N_SAMPLES, block_size=BLOCK)
    acc.add_rows(blocks[0])
    jax.block_until_ready(acc.G)

    # Steady-state accumulation.
    acc = GramianAccumulator(N_SAMPLES, block_size=BLOCK)
    processed = 0
    start = time.perf_counter()
    i = 0
    while True:
        acc.add_rows(blocks[i % WORKING_SET_BLOCKS])
        processed += BLOCK
        i += 1
        if i % 16 == 0 and time.perf_counter() - start > MIN_BENCH_SECONDS:
            break
    jax.block_until_ready(acc.G)
    accumulate_seconds = time.perf_counter() - start
    variants_per_sec = processed / accumulate_seconds

    # Finalize at full cohort size, entirely on device; the only fetch is
    # the final (N, 2) components.
    start = time.perf_counter()
    S = acc.finalize_device()
    B = gower_center(S)
    components, eigenvalues = principal_components_subspace(B, 2)
    result = np.asarray(jax.device_get(components))
    finalize_seconds = time.perf_counter() - start
    assert result.shape == (N_SAMPLES, 2)

    projected = WHOLE_GENOME_VARIANTS / variants_per_sec + finalize_seconds

    print(
        json.dumps(
            {
                "metric": (
                    "1000G whole-genome PCoA wall-clock "
                    f"(projected, {N_SAMPLES} samples, {WHOLE_GENOME_VARIANTS} variants)"
                ),
                "value": round(projected, 3),
                "unit": "s",
                "vs_baseline": round(BASELINE_SECONDS / projected, 2),
                "details": {
                    "variants_per_sec_per_chip": round(variants_per_sec),
                    "accumulate_seconds_measured": round(accumulate_seconds, 3),
                    "variants_measured": processed,
                    "finalize_seconds": round(finalize_seconds, 3),
                    "blockgen_seconds_per_block_host": round(
                        gen_seconds / WORKING_SET_BLOCKS, 3
                    ),
                    "device": str(device),
                    "baseline": "~7200 s on 40 CPU cores (reference README)",
                },
            }
        )
    )


if __name__ == "__main__":
    main()

"""Benchmark: 1000 Genomes whole-genome PCoA on one TPU chip, end to end.

Baseline (BASELINE.md): the reference runs the whole-genome 1KG phase 1 PCoA
(2,504 samples, ~39.4M variant sites) in ~2 hours on 40 CPU cores
(``/root/reference/README.md:126-138``). North star: < 5 minutes on a v5e-8.

This is a TRUE ingest-inclusive run of the flagship pipeline
(``VariantsPcaDriver``), not a projection:

- the synthetic cohort is sized to the real workload: 2,504 samples and a
  site grid of ≥39.4M candidate sites across the 22 autosomes
  (``--all-references`` semantics, spacing 73 ≈ 2.88 Gb / 39.4M);
- ingest is INSIDE the timed region: the host streams per-site thresholds
  (the variant-metadata plane) while the device generates the genotype data
  plane and accumulates the Gramian, fused per dispatch
  (``ops/devicegen.py``);
- finalize (Gower centering + subspace-iteration PCA of the 2504×2504
  matrix) and the result fetch are inside the timed region;
- only compilation is excluded (warmed on a small contig first; the
  persistent cache makes it a no-op on reruns). Honest-timing note: on this
  remote-attached backend ``block_until_ready`` can ACK before execution
  completes, so the run is timed to the fetched (N, num_pc) result — nothing
  is left in flight.

Prints exactly one JSON line (driver stage prints are redirected to stderr).
"""

import argparse
import contextlib
import json
import shutil
import os
import sys
import time

import numpy as np

N_SAMPLES = 2504
VARIANT_SPACING = 73  # 2.881 Gb autosomes / 73 = 39.5M sites >= 1KG's 39.4M
BASELINE_SECONDS = 7200.0
# Measured optimum on v5e (DESIGN.md "single-chip ingest roofline"): large
# dispatch groups amortize per-dispatch overhead; contig remainders run
# through the accumulator's ~K/8 tail program, so group padding stays <2%.
# BLOCKS_PER_DISPATCH defaults to the driver's constant-work auto rule
# (small cohorts get longer scans — ops/devicegen.py:auto_blocks_per_dispatch,
# platinum ~2× faster: 1.03 → 0.53 s); BENCH_BLOCKS_PER_DISPATCH pins it.
BLOCK = int(os.environ.get("BENCH_BLOCK", 16384))
BLOCKS_PER_DISPATCH = (
    int(os.environ["BENCH_BLOCKS_PER_DISPATCH"])
    if "BENCH_BLOCKS_PER_DISPATCH" in os.environ
    else None
)

# The BASELINE.json benchmark configs (plus a beyond-reference large-cohort
# demo). Only whole-genome has a published
# reference number (7200 s); the others report wall-clock with
# vs_baseline=null.
CONFIGS = {
    "whole-genome": {
        "metric": "1000G whole-genome PCoA wall-clock",
        "args": ["--all-references"],
        "sets": ["bench-1kg"],
        "baseline_seconds": BASELINE_SECONDS,
    },
    "brca1": {
        "metric": "BRCA1-region PCoA wall-clock (reference default config)",
        "args": ["--references", "17:41196311:41277499"],
        "sets": ["bench-1kg"],
        "baseline_seconds": None,
    },
    "chr17": {
        "metric": "single-chromosome (chr17) PCoA wall-clock",
        "args": ["--references", "17:0:81195210"],
        "sets": ["bench-1kg"],
        "baseline_seconds": None,
    },
    "platinum": {
        # Platinum Genomes is a SMALL deep-call cohort (~17 genomes), not a
        # second 2,504-sample set — the honest model of the reference's
        # second public variant set (``SearchVariantsExample.scala:28``).
        "metric": "Platinum-style deep-call cohort (17 samples) whole-genome PCoA wall-clock",
        "args": ["--all-references"],
        "sets": ["bench-platinum"],
        "num_samples": 17,
        "baseline_seconds": None,
    },
    "large-cohort": {
        # Beyond-reference scale demo: a 25,000-sample cohort (10x 1KG) —
        # the regime the reference's in-memory strategy guidance warns about
        # (~50K samples ~ 20 GB, VariantsPca.scala:216-217). No strategy
        # override: the HBM-derived auto rule
        # (ops/gramian.py:dense_strategy_fits) picks dense here (the int32
        # Gramian is 2.5 GB; ~4 working copies still fit v5e's 16 GB).
        "metric": "large-cohort (25,000 samples) chr17 PCoA wall-clock",
        "args": ["--references", "17:0:81195210"],
        "sets": ["bench-1kg"],
        "num_samples": 25_000,
        "baseline_seconds": None,
    },
    "large-cohort-sharded": {
        # The SHARDED large-cohort regime (getSimilarityMatrixStream's
        # memory-bounded analog): same 25,000-sample chr17 workload forced
        # through the samples-sharded ring so the bit-packed, overlapped
        # ring exchange (ops/gramian.py:_ring_tiles) is measured — and its
        # gramian_ring_bytes manifest counter surfaces packed-vs-unpacked
        # ICI traffic directly. Needs >= 2 devices for a samples axis; the
        # mesh is resolved at runtime (all devices on samples).
        "metric": "large-cohort (25,000 samples) chr17 sharded-ring PCoA wall-clock",
        "args": ["--references", "17:0:81195210"],
        "sets": ["bench-1kg"],
        "num_samples": 25_000,
        "sharded": True,
        "baseline_seconds": None,
    },
    "merged": {
        # The reference's ACTUAL joint-cohort scenario: 1000 Genomes (2,504
        # samples) joined with Platinum (~17 deep genomes) at shared sites
        # (``VariantsPca.scala:155-168``) — an ASYMMETRIC 2,521-column join,
        # not two identical cohorts. ONE references list for both sets (the
        # Scala zip-truncation semantics, GenomicsConf.scala:91-95): each
        # autosome is scanned once.
        "metric": "merged 1000G+Platinum joint-cohort PCoA wall-clock (2521 columns)",
        "args": ["--references", "AUTOSOMES"],
        "sets": ["bench-1kg", "bench-platinum"],
        "cohort_sizes": {"bench-platinum": 17},
        "baseline_seconds": None,
    },
}


# -------------------------------------------------------------- analyses bench
# The population-genetics analyses (analyses/: GRM/kinship, windowed LD
# pruning, association scan) ride the host-fed packed block stream — the
# per-site workload layer on the same substrate. Each config is a
# chromosome-17-scale synthetic cohort (1KG sample count) reported from the
# run MANIFEST like every device config; wall-clock includes the analysis's
# (small, stateless) kernel compiles — there is no PCA-style warmup split
# because per-block kernels compile once in milliseconds, not tens of
# seconds.

ANALYSIS_REFERENCES = "17:0:81195210"

ANALYSIS_CONFIGS = {
    "grm": {
        "metric": (
            "GRM/kinship (VanRaden, 2,504 samples, chr17) wall-clock"
        ),
    },
    "ld-prune": {
        "metric": (
            "windowed LD r² prune (2,504 samples, chr17, window 256) "
            "wall-clock"
        ),
    },
    "assoc-scan": {
        "metric": (
            "per-site case/control chi-square scan (2,504 samples, chr17) "
            "wall-clock"
        ),
    },
}


# ------------------------------------------------------------ serve bench
# The serving layer under mixed traffic: an in-process resident service
# (serve/) with executor slices, driven through the REAL HTTP API by
# concurrent submitters. Reports P50/P99 per admission class in two
# phases — small jobs alone (unloaded), then small jobs while a large job
# holds the large slice (loaded) — so the number that matters to users
# ("does a cheap query stall behind a whole-genome run?") is measured,
# not argued. ci.sh asserts loaded small P99 <= ~2x unloaded.

SERVE_LOAD_SMALL_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]
SERVE_LOAD_LARGE_FLAGS = [
    "--num-samples",
    "16",
    "--references",
    "1:0:2500000",
]
#: Classify the large-phase job as LARGE without waiting minutes on CPU:
#: the limit sits between the small (~500 sites) and large (~25k sites)
#: shapes above.
SERVE_LOAD_SITE_LIMIT = 5_000
SERVE_LOAD_SMALL_JOBS = 12


def _percentile(values, fraction: float) -> float:
    ordered = sorted(values)
    index = min(len(ordered) - 1, int(round(fraction * (len(ordered) - 1))))
    return ordered[index]


def _small_wall_snapshot(service) -> dict:
    """One merged ``serve_job_wall_seconds`` snapshot for the small
    admission class (all kinds and compile dispositions) — the same
    histograms ``/v1/fleet/stats`` and the calibration ledger ride on,
    so the bench reports the numbers operators will actually see."""
    from spark_examples_tpu.obs.metrics import SERVE_JOB_WALL_SECONDS

    merged = {"buckets": {}, "sum": 0.0, "count": 0}
    family = service.registry.get(SERVE_JOB_WALL_SECONDS)
    if family is None:
        return merged
    for child in family.children():
        if child.labels_dict.get("job_class") != "small":
            continue
        snap = child.snapshot()
        for bound, cumulative in snap["buckets"].items():
            merged["buckets"][bound] = merged["buckets"].get(bound, 0) + int(
                cumulative
            )
        merged["sum"] += float(snap["sum"])
        merged["count"] += int(snap["count"])
    return merged


def _snapshot_delta(after: dict, before: dict) -> dict:
    """The histogram increments one bench phase contributed: cumulative
    bucket counts subtract bound-by-bound (children of one family share
    bounds, and counts only grow)."""
    bounds = set(after["buckets"]) | set(before["buckets"])
    return {
        "buckets": {
            bound: after["buckets"].get(bound, 0)
            - before["buckets"].get(bound, 0)
            for bound in bounds
        },
        "sum": after["sum"] - before["sum"],
        "count": after["count"] - before["count"],
    }


def _phase_quantiles(delta: dict, phase: str) -> dict:
    from spark_examples_tpu.obs.metrics import histogram_quantile

    if delta["count"] <= 0:
        raise RuntimeError(
            f"serve-load {phase} phase recorded no small-job wall samples"
        )
    return {
        "count": delta["count"],
        "mean": round(delta["sum"] / delta["count"], 4),
        "p50": round(histogram_quantile(delta, 0.50), 4),
        "p99": round(histogram_quantile(delta, 0.99), 4),
    }


#: Identical small jobs per fused-batch phase group: enough lanes that a
#: one-dispatch group visibly amortizes per-job device dispatch, small
#: enough that the serial reference stays quick on CPU.
SERVE_FUSED_GROUP_JOBS = 6
#: Cheap jobs queued behind the expensive job in the ordering phase.
SERVE_ORDERING_CHEAP_JOBS = 6
#: The ordering phase's expensive shape: compute-bound (the N² Gramian
#: update, not the site count) so its WARM run holds the single worker
#: long enough that the cheap jobs demonstrably queue behind (FIFO) or
#: jump past (cost) the second expensive submission.
SERVE_ORDERING_EXPENSIVE_FLAGS = [
    "--num-samples",
    "128",
    "--references",
    "1:0:10000000",
]
#: One class lane for the whole ordering phase: the site limit sits
#: ABOVE the expensive shape, so cheap and expensive share a lane and
#: the ordering under test is within-lane.
SERVE_ORDERING_SITE_LIMIT = 500_000


def _submit_small_jobs(service, flags, count) -> list:
    from spark_examples_tpu.serve.protocol import request_doc

    ids = []
    for _ in range(count):
        status, doc = service.submit(request_doc(flags))
        if status != 202:
            raise RuntimeError(f"serve bench submit rejected {status}: {doc}")
        ids.append(doc["job"]["id"])
    return ids


def _wait_jobs(service, ids, timeout: float = 600.0) -> list:
    jobs = []
    deadline = time.time() + timeout
    for jid in ids:
        while True:
            _, doc = service.job_status(jid)
            job = doc["job"]
            if job["status"] in ("done", "failed", "cancelled"):
                break
            if time.time() > deadline:
                raise RuntimeError(f"serve bench timed out waiting on {jid}")
            time.sleep(0.02)
        if job["status"] != "done":
            raise RuntimeError(f"serve bench job failed: {job}")
        jobs.append(job)
    return jobs


def _run_fused_group(batch_fuse: bool) -> dict:
    """One group of identical small jobs through an in-process service:
    fusion on (one stacked device program per group) or off (the same
    batch group back to back). Returns the group's summed executor
    seconds, its result rows (for the byte-parity check), and the
    dispatch counters proving which path ran."""
    import tempfile

    from spark_examples_tpu.serve.daemon import PcaService

    run_dir = tempfile.mkdtemp(prefix="serve_fused_")
    service = PcaService(
        run_dir=run_dir,
        small_slices=0,
        batch_fuse=batch_fuse,
        batch_max_jobs=SERVE_FUSED_GROUP_JOBS,
        batch_linger_seconds=2.0,
    ).start()
    try:
        # Warmup one FULL group, not one job: the serial path's per-job
        # program and the fused path's K-lane stacked program both
        # compile here, so the measured group compares steady-state
        # dispatch (the resident daemon's compile-once regime), not one
        # path's cold compile against the other's warm cache.
        _wait_jobs(
            service,
            _submit_small_jobs(
                service, SERVE_LOAD_SMALL_FLAGS, SERVE_FUSED_GROUP_JOBS
            ),
        )
        t0 = time.perf_counter()
        ids = _submit_small_jobs(
            service, SERVE_LOAD_SMALL_FLAGS, SERVE_FUSED_GROUP_JOBS
        )
        jobs = _wait_jobs(service, ids)
        wall = time.perf_counter() - t0
        dispatch = service.fleet_stats()["dispatch"]
    finally:
        service.stop(timeout=60)
        shutil.rmtree(run_dir, ignore_errors=True)
    return {
        "executor_seconds": sum(job["seconds"] for job in jobs),
        "client_wall_seconds": wall,
        "pc_lines": [job["result"]["pc_lines"] for job in jobs],
        "fused_sizes": [job["fused_size"] for job in jobs],
        "dispatch": dispatch,
    }


def _run_fused_batch_phase() -> dict:
    """The fused-batch phase: one K-job group fused (one device program)
    vs the identical group with ``--no-batch-fuse`` (back to back),
    byte-parity asserted, group throughput compared."""
    fused = _run_fused_group(batch_fuse=True)
    serial = _run_fused_group(batch_fuse=False)
    if fused["dispatch"]["fused_groups"] < 1:
        raise RuntimeError(
            f"fused-batch phase never fused a group: {fused['dispatch']}"
        )
    if serial["dispatch"]["fused_groups"] != 0:
        raise RuntimeError(
            f"--no-batch-fuse config fused anyway: {serial['dispatch']}"
        )
    reference = serial["pc_lines"][0]
    for source, lines_per_job in (("fused", fused["pc_lines"]),
                                  ("serial", serial["pc_lines"])):
        for lines in lines_per_job:
            if lines != reference:
                raise RuntimeError(
                    f"fused-batch phase {source} results diverged from the "
                    "serial reference — byte parity broken"
                )
    throughput_ratio = (
        serial["executor_seconds"] / fused["executor_seconds"]
        if fused["executor_seconds"] > 0
        else None
    )
    return {
        "group_jobs": SERVE_FUSED_GROUP_JOBS,
        "byte_identical": True,
        "fused": {
            "executor_seconds": round(fused["executor_seconds"], 4),
            "client_wall_seconds": round(fused["client_wall_seconds"], 4),
            "fused_sizes": fused["fused_sizes"],
            "dispatch": fused["dispatch"],
        },
        "serial": {
            "executor_seconds": round(serial["executor_seconds"], 4),
            "client_wall_seconds": round(serial["client_wall_seconds"], 4),
            "dispatch": serial["dispatch"],
        },
        # >1 means the one-program group outran the same jobs back to
        # back on the identical warm service.
        "group_throughput_ratio": (
            round(throughput_ratio, 3) if throughput_ratio is not None else None
        ),
    }


def _run_ordering_config(ordering: str) -> dict:
    """Mixed load through one worker lane under the given queue
    ordering: an expensive job queued FIRST, cheap jobs behind it, all
    while a blocker holds the worker — cost ordering should pop the
    cheap jobs past the expensive one, FIFO must not."""
    import tempfile

    from spark_examples_tpu.serve.daemon import PcaService
    from spark_examples_tpu.serve.protocol import request_doc

    run_dir = tempfile.mkdtemp(prefix="serve_order_")
    service = PcaService(
        run_dir=run_dir,
        small_slices=0,
        ordering=ordering,
        small_site_limit=SERVE_ORDERING_SITE_LIMIT,
        batch_max_jobs=SERVE_ORDERING_CHEAP_JOBS,
    ).start()
    try:
        # Warm both geometries so the measured phase compares scheduling,
        # not compilation.
        _wait_jobs(service, _submit_small_jobs(service, SERVE_LOAD_SMALL_FLAGS, 1))
        _wait_jobs(
            service,
            _submit_small_jobs(service, SERVE_ORDERING_EXPENSIVE_FLAGS, 1),
        )
        # The blocker occupies the worker while the contested queue forms.
        blocker = _submit_small_jobs(
            service, SERVE_ORDERING_EXPENSIVE_FLAGS, 1
        )
        expensive = _submit_small_jobs(
            service, SERVE_ORDERING_EXPENSIVE_FLAGS, 1
        )
        cheap = _submit_small_jobs(
            service, SERVE_LOAD_SMALL_FLAGS, SERVE_ORDERING_CHEAP_JOBS
        )
        jobs = _wait_jobs(service, blocker + expensive + cheap)
    finally:
        service.stop(timeout=60)
        shutil.rmtree(run_dir, ignore_errors=True)
    cheap_latency = [
        job["finished_unix"] - job["submitted_unix"] for job in jobs[2:]
    ]
    return {
        "ordering": ordering,
        "cheap_jobs": SERVE_ORDERING_CHEAP_JOBS,
        "cheap_p50_seconds": round(_percentile(cheap_latency, 0.5), 4),
        "cheap_p99_seconds": round(_percentile(cheap_latency, 0.99), 4),
        "expensive_latency_seconds": round(
            jobs[1]["finished_unix"] - jobs[1]["submitted_unix"], 4
        ),
    }


def _run_cost_ordering_phase() -> dict:
    """Cost-ordered scheduling vs FIFO on the identical mixed load: the
    number that justifies SJF-within-class — how much queue-wait the
    cheap jobs stop paying for one expensive job ahead of them."""
    cost = _run_ordering_config("cost")
    fifo = _run_ordering_config("fifo")
    ratio = (
        fifo["cheap_p99_seconds"] / cost["cheap_p99_seconds"]
        if cost["cheap_p99_seconds"] > 0
        else None
    )
    return {
        "cost": cost,
        "fifo": fifo,
        # >1 means cost ordering cut the cheap jobs' P99 vs FIFO.
        "fifo_over_cost_p99": round(ratio, 3) if ratio is not None else None,
    }


def _serve_load_phase(client, jobs: int) -> list:
    """Submit ``jobs`` small jobs one after another (a poller's view:
    submit -> terminal), returning per-job wall seconds."""
    latencies = []
    for _ in range(jobs):
        t0 = time.perf_counter()
        doc = client.submit(SERVE_LOAD_SMALL_FLAGS)
        job = client.wait(doc["job"]["id"], timeout=300, poll_cap_seconds=0.1)
        if job["job"]["status"] != "done":
            raise RuntimeError(f"serve-load small job failed: {job}")
        latencies.append(time.perf_counter() - t0)
    return latencies


def _run_serve_load_config(device) -> dict:
    import tempfile

    import jax

    from spark_examples_tpu.serve.client import ServeClient
    from spark_examples_tpu.serve.daemon import PcaService
    from spark_examples_tpu.serve.http import start_server

    device_count = len(jax.devices())
    run_dir = tempfile.mkdtemp(prefix="serve_load_")
    service = PcaService(
        run_dir=run_dir,
        small_slices=None,  # auto: 1 small slice when a device is spare
        small_site_limit=SERVE_LOAD_SITE_LIMIT,
    ).start()
    server = start_server(service)
    client = ServeClient(server.url)
    sliced = len(service._workers) > 1
    try:
        # Warmup: compile the small geometry once (cold compile is the
        # daemon's startup cost, not a steady-state latency).
        warm = client.submit(SERVE_LOAD_SMALL_FLAGS)
        client.wait(warm["job"]["id"], timeout=300, poll_cap_seconds=0.1)

        baseline_snap = _small_wall_snapshot(service)
        unloaded = _serve_load_phase(client, SERVE_LOAD_SMALL_JOBS)
        unloaded_snap = _small_wall_snapshot(service)

        large_doc = client.submit(SERVE_LOAD_LARGE_FLAGS)
        large_id = large_doc["job"]["id"]
        if large_doc["job"]["class"] != "large":
            raise RuntimeError(
                f"serve-load large job classified {large_doc['job']['class']}"
            )
        t_large = time.perf_counter()
        loaded = _serve_load_phase(client, SERVE_LOAD_SMALL_JOBS)
        loaded_snap = _small_wall_snapshot(service)
        large = client.wait(large_id, timeout=600, poll_cap_seconds=0.2)
        large_seconds = time.perf_counter() - t_large
        if large["job"]["status"] != "done":
            raise RuntimeError(f"serve-load large job failed: {large}")
        health = client.healthz()
        # The observability surface under test: the HTTP fleet-stats
        # document must exist and carry the same class quantiles.
        import urllib.request

        with urllib.request.urlopen(
            server.url + "/v1/fleet/stats", timeout=30
        ) as resp:
            fleet = json.loads(resp.read().decode("utf-8"))
    finally:
        server.shutdown()
        service.stop(timeout=60)
        shutil.rmtree(run_dir, ignore_errors=True)

    # The fused-batch and queue-ordering phases ride their own
    # single-lane services (the contested topology each needs), after
    # the mixed-load service released the devices.
    fused_batch = _run_fused_batch_phase()
    cost_ordering = _run_cost_ordering_phase()

    unloaded_stats = _phase_quantiles(
        _snapshot_delta(unloaded_snap, baseline_snap), "unloaded"
    )
    loaded_stats = _phase_quantiles(
        _snapshot_delta(loaded_snap, unloaded_snap), "loaded"
    )
    unloaded_p99 = unloaded_stats["p99"]
    loaded_p99 = loaded_stats["p99"]
    ratio = loaded_p99 / unloaded_p99 if unloaded_p99 > 0 else None
    return {
        "metric": (
            "small-job P99 under concurrent large-job load vs unloaded "
            "(resident service, executor slices)"
        ),
        "value": round(ratio, 3) if ratio is not None else None,
        "unit": "x",
        "vs_baseline": None,
        "details": {
            "devices": device_count,
            "slices": [
                {"name": s["name"], "devices": s["devices"]}
                for s in health["slices"]
            ],
            "sliced": sliced,
            "small_jobs_per_phase": SERVE_LOAD_SMALL_JOBS,
            # Server-side wall quantiles from `serve_job_wall_seconds`
            # snapshot deltas — the metric `/v1/fleet/stats` serves.
            "small_unloaded_seconds": unloaded_stats,
            "small_loaded_seconds": loaded_stats,
            # Client-observed submit->terminal latency, for comparison
            # with the server-side histograms (includes HTTP + polling).
            "client_observed_seconds": {
                "unloaded_p50": round(_percentile(unloaded, 0.5), 4),
                "unloaded_p99": round(_percentile(unloaded, 0.99), 4),
                "loaded_p50": round(_percentile(loaded, 0.5), 4),
                "loaded_p99": round(_percentile(loaded, 0.99), 4),
            },
            "fleet_stats": {
                "classes": fleet.get("classes"),
                "calibration": fleet.get("calibration"),
                "dispatch": fleet.get("dispatch"),
                "counters": fleet.get("counters"),
            },
            # One K-job group fused (one stacked device program) vs the
            # identical group back to back, byte parity asserted.
            "fused_batch": fused_batch,
            # Cost-ordered (SJF) vs FIFO on the identical mixed load.
            "cost_ordering": cost_ordering,
            "large_job_seconds": round(
                large["job"]["seconds"] or large_seconds, 3
            ),
            "loaded_over_unloaded_p99": (
                round(ratio, 3) if ratio is not None else None
            ),
            "device": str(device),
        },
    }


# --------------------------------------------------------- multihost bench
# Pod ingest scaling: a REAL 2-process gloo fleet (parallel/multihost.py)
# running the unmodified variants-pca CLI with HOST-SHARDED ingest — each
# process reads only its contig partition. The headline number is the
# largest per-host share of the solo run's ingested reference bases: ~1/H
# means ingest bandwidth scales linearly with hosts (the PR's claim), 1.0
# would mean every host still reads everything. Correctness rides along:
# the report is only accepted when the fleet's PC rows are byte-identical
# to the solo oracle and every per-host conformance bound holds.

MULTIHOST_PROCESSES = 2
MULTIHOST_LOCAL_DEVICES = 2


def _run_multihost_config(device) -> dict:
    from spark_examples_tpu.parallel.multihost import verify_multihost

    report = verify_multihost(
        num_processes=MULTIHOST_PROCESSES,
        local_devices=MULTIHOST_LOCAL_DEVICES,
    )
    if not report.get("ok"):
        raise RuntimeError(
            "multihost fleet rehearsal failed: "
            + json.dumps({k: v for k, v in report.items() if k != "children"})
        )
    bases = report["fleet_io_reference_bases"]
    solo_bases = int(bases["solo"])
    per_process = [int(b) for b in bases["per_process"]]
    fractions = [round(b / solo_bases, 4) for b in per_process]
    max_fraction = max(fractions)
    return {
        "metric": (
            f"host-sharded pod ingest: largest per-host share of solo "
            f"ingest bytes ({MULTIHOST_PROCESSES}-process gloo fleet, "
            "PC rows byte-identical to the solo oracle)"
        ),
        "value": max_fraction,
        "unit": "fraction of solo ingest per host",
        # Baseline: the pre-host-sharding data path, where every host read
        # the whole input (fraction 1.0 per host).
        "vs_baseline": round(1.0 / max_fraction, 2) if max_fraction else None,
        "details": {
            "num_processes": MULTIHOST_PROCESSES,
            "local_devices_per_process": MULTIHOST_LOCAL_DEVICES,
            "solo_reference_bases": solo_bases,
            "per_process_reference_bases": per_process,
            "per_process_fraction_of_solo": fractions,
            "partition_sum_exact": sum(per_process) == solo_bases,
            "wall_seconds": report.get("fleet_wall_seconds"),
            "cli_outputs_identical": report["cli_outputs_identical"],
            "cli_pc_lines": report["cli_pc_lines"],
            "hier_gramian_ok": report["hier_gramian_ok"],
            "fleet_conformance_ok": report["fleet_conformance_ok"],
            "fleet_trace_ok": report["fleet_trace_ok"],
            "device": str(device),
            "baseline": (
                "every host reading the whole input (per-host fraction 1.0; "
                "the pre-pod-ingest data path)"
            ),
        },
    }


def _write_bench_phenotypes(path: str, conf) -> None:
    """A balanced case/control TSV over the synthetic cohort's real
    callset names (the assoc verb's strict both-ways coverage check)."""
    from spark_examples_tpu.pipeline.pca_driver import make_source

    names = [
        cs["name"]
        for cs in make_source(conf).search_callsets(conf.variant_set_id)
    ]
    with open(path, "w") as f:
        for i, name in enumerate(names):
            f.write(f"{name}\t{i % 2}\n")


def _run_analysis_config(name: str, device) -> dict:
    import tempfile

    from spark_examples_tpu.obs.manifest import validate_manifest

    tmpdir = tempfile.mkdtemp(prefix="analyses_bench_")
    try:
        manifest_path = os.path.join(tmpdir, "manifest.json")
        base = [
            "--num-samples", str(N_SAMPLES),
            "--references", ANALYSIS_REFERENCES,
            "--block-size", "4096",
            "--metrics-json", manifest_path,
        ]
        if name == "grm":
            from spark_examples_tpu.analyses.grm import run_grm_pipeline
            from spark_examples_tpu.config import GrmConf

            conf = GrmConf.parse(base)
            start = time.perf_counter()
            result = run_grm_pipeline(conf)
            wall = time.perf_counter() - start
            manifest = result.manifest
            extra = {"kinship_summary": result.summary}
        elif name == "ld-prune":
            from spark_examples_tpu.analyses.ld import run_ld_pipeline
            from spark_examples_tpu.config import LdConf

            conf = LdConf.parse(
                base + ["--ld-r2-threshold", "0.2", "--ld-window-sites", "256"]
            )
            start = time.perf_counter()
            result = run_ld_pipeline(conf)
            wall = time.perf_counter() - start
            manifest = result.manifest
            extra = {
                "sites_kept": result.sites_kept,
                "kept_fraction": (
                    round(result.sites_kept / result.sites_tested, 4)
                    if result.sites_tested
                    else None
                ),
            }
        else:  # assoc-scan
            from spark_examples_tpu.analyses.assoc import run_assoc_pipeline
            from spark_examples_tpu.config import AssocConf

            phenotypes = os.path.join(tmpdir, "phenotypes.tsv")
            conf = AssocConf.parse(base + ["--phenotypes", phenotypes])
            _write_bench_phenotypes(phenotypes, conf)
            start = time.perf_counter()
            result = run_assoc_pipeline(conf)
            wall = time.perf_counter() - start
            manifest = result.manifest
            extra = {
                "cases": result.n_cases,
                "controls": result.n_controls,
                "top_chi2": result.top[0][0] if result.top else None,
            }
        schema_errors = validate_manifest(manifest)
        assert not schema_errors, schema_errors
        analysis = manifest["analysis"]
        sites = int(analysis["sites_tested"])
        return {
            "metric": ANALYSIS_CONFIGS[name]["metric"],
            "value": round(wall, 3),
            "unit": "s",
            "vs_baseline": None,
            "details": {
                "analysis": analysis,
                "sites_per_sec": round(sites / wall) if wall > 0 else None,
                "compile_seconds_excluded": 0.0,
                **extra,
                "device": str(device),
                "baseline": (
                    "no published reference number for this analysis"
                ),
            },
        }
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------- ingest bench
# The file-ingest data plane (chunk-parallel native parse + prefetch +
# double-buffered device feed) is benchmarked apart from the device configs:
# it is host-side, deterministic, and the one stage the 2h/40-core baseline
# was actually bound by (SURVEY.md §7 — ingest, not math).

INGEST_FIXTURE_SAMPLES = 64
INGEST_FIXTURE_ROWS = 40_000  # × ~3-400 B/row ≈ 12 MB decompressed


def _write_ingest_fixture(path: str) -> None:
    rng = np.random.default_rng(20_24)
    gt_choices = np.array(["0|0", "0|1", "1|1", ".|."])
    with open(path, "w") as f:
        f.write("##fileformat=VCFv4.2\n")
        f.write(
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
            + "\t".join(f"S{i:03d}" for i in range(INGEST_FIXTURE_SAMPLES))
            + "\n"
        )
        gts = gt_choices[
            rng.integers(0, len(gt_choices),
                         (INGEST_FIXTURE_ROWS, INGEST_FIXTURE_SAMPLES))
        ]
        for k in range(INGEST_FIXTURE_ROWS):
            info = f"AF={rng.random():.4f}" if k % 4 else "NS=2"
            f.write(
                f"17\t{100 + 37 * k}\t.\tAC\tG\t.\t.\t{info}\tGT\t"
                + "\t".join(gts[k])
                + "\n"
            )


def _run_ingest_config(device) -> dict:
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="ingest_bench_")
    try:
        return _run_ingest_measurements(tmpdir, device)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def _run_ingest_measurements(tmpdir: str, device) -> dict:
    from spark_examples_tpu.ops.gramian import GramianAccumulator
    from spark_examples_tpu.pipeline.datasets import PrefetchIterator
    from spark_examples_tpu.sources.files import (
        _PackedVcf,
        _StreamedVcf,
        default_ingest_workers,
    )
    from spark_examples_tpu.utils.native import native_unavailable_reason

    path = os.path.join(tmpdir, "bench.vcf")
    _write_ingest_fixture(path)
    size_mb = os.path.getsize(path) / 1e6

    # Parse throughput vs worker count, best of 2 (first run also pays the
    # one-time native build; the repeat isolates steady-state parse).
    counts = sorted({0, 1, 2, 4, default_ingest_workers()})
    seconds = {}
    for workers in counts:
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            view = _PackedVcf(path, "bench", ingest_workers=workers)
            best = min(best, time.perf_counter() - t0)
        seconds[workers] = best
    native = view.native
    per_worker = {
        str(w): {
            "seconds": round(s, 3),
            "mb_per_s": round(size_mb / s, 1),
            "speedup_vs_serial": round(seconds[0] / s, 2),
        }
        for w, s in seconds.items()
    }

    # Ingest/compute overlap: the streamed fixture through the bounded
    # prefetch queue into the double-buffered Gramian feed — the exact
    # driver wiring (pipeline/pca_driver.py:_similarity_stage), measured at
    # the component seam so the numbers are profiler-free.
    view = _StreamedVcf(
        path, "bench", chunk_bytes=1 << 20,
        ingest_workers=default_ingest_workers(),
    )
    acc = GramianAccumulator(
        INGEST_FIXTURE_SAMPLES, block_size=2048, pipeline_depth=2
    )
    t0 = time.perf_counter()
    prefetch = PrefetchIterator(
        (hv for _, _, _, _, hv in view.iter_chunk_arrays()), depth=2
    )
    try:
        for hv in prefetch:
            acc.add_rows(hv)
        wall = time.perf_counter() - t0
        acc.finalize_device()
    finally:
        prefetch.close()
    # Structured overlap accounting straight from the iterator (the same
    # dict the run manifest embeds); the one-line report rides along for
    # humans reading the JSON.
    overlap = {
        "wall_seconds": round(wall, 3),
        **{
            key: round(value, 3) if isinstance(value, float) else value
            for key, value in prefetch.overlap_stats().items()
        },
        "report": prefetch.overlap_report(),
    }

    best_workers = min(seconds, key=seconds.get)
    return {
        "metric": (
            f"chunk-parallel native VCF parse ({size_mb:.1f} MB, "
            f"{INGEST_FIXTURE_ROWS} rows × {INGEST_FIXTURE_SAMPLES} samples)"
        ),
        "value": per_worker[str(best_workers)]["mb_per_s"],
        "unit": "MB/s",
        "vs_baseline": per_worker[str(best_workers)]["speedup_vs_serial"],
        "details": {
            "native_parser": native,
            "native_unavailable_reason": (
                None if native else native_unavailable_reason()
            ),
            "host_cpus": os.cpu_count(),
            "default_ingest_workers": default_ingest_workers(),
            "parse_by_workers": per_worker,
            "ingest_compute_overlap": overlap,
            "baseline": "serial oracle path (--ingest-workers 0), same host",
            "device": str(device),
        },
    }


def _autosome_references() -> str:
    from spark_examples_tpu.constants import Examples

    return ",".join(
        f"{name}:0:{length}"
        for name, length in Examples.HUMAN_CHROMOSOMES.items()
        if name not in ("X", "Y")
    )


def _make_driver(conf_args, source):
    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver

    conf = PcaConf.parse(conf_args)
    return conf, VariantsPcaDriver(conf, source)


def _run_config(name: str, device) -> dict:
    import jax

    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    config = CONFIGS[name]
    if config.get("sharded") and len(jax.devices()) < 2:
        return {
            "metric": config["metric"],
            "value": None,
            "unit": "s",
            "vs_baseline": None,
            "details": {
                "skipped": "sharded ring needs >= 2 devices for a samples "
                f"axis; have {len(jax.devices())}",
                "device": str(device),
            },
        }
    n_sets = len(config["sets"])
    n_samples = config.get("num_samples", N_SAMPLES)
    cohort_sizes = config.get("cohort_sizes")
    per_set_sizes = [
        (cohort_sizes or {}).get(s, n_samples) for s in config["sets"]
    ]
    total_columns = sum(per_set_sizes)
    from spark_examples_tpu.ops.devicegen import auto_blocks_per_dispatch

    # Resolve the scan length the driver will use (explicit env pin, or the
    # constant-work auto rule) — the warmup region must cover one full
    # group of the SAME length or the measured run compiles cold.
    k_resolved = BLOCKS_PER_DISPATCH or auto_blocks_per_dispatch(
        total_columns, BLOCK
    )
    warmup_bases = VARIANT_SPACING * (
        BLOCK * k_resolved + BLOCK * max(1, k_resolved // 8)
    )
    base_args = [
        "--variant-set-id", ",".join(config["sets"]),
        "--ingest", "device",
        "--block-size", str(BLOCK),
        "--num-pc", "2",
        # Per-set cohort sizes; the dense/sharded strategy is left on auto —
        # the HBM-derived rule decides (ops/gramian.py:dense_strategy_fits).
        "--num-samples", ",".join(str(s) for s in per_set_sizes),
    ]
    if BLOCKS_PER_DISPATCH is not None:
        base_args += ["--blocks-per-dispatch", str(BLOCKS_PER_DISPATCH)]
    if config.get("sharded"):
        # All devices on the samples axis: the ring spans the whole chip
        # set and every device holds one row tile of the padded Gramian.
        base_args += [
            "--mesh-shape", f"1,{len(jax.devices())}",
            "--similarity-strategy", "sharded",
        ]
    source = SyntheticGenomicsSource(
        num_samples=n_samples,
        seed=42,
        variant_spacing=VARIANT_SPACING,
        cohort_sizes=cohort_sizes,
    )

    # Warmup: identical shapes (one dispatch group + full-cohort finalize),
    # so every jit in the measured run is compile-cache warm.
    warm_start = time.perf_counter()
    warm_refs = ";".join([f"1:0:{warmup_bases}"] * n_sets)
    conf_w, driver_w = _make_driver(
        base_args + ["--references", warm_refs], source
    )
    contigs_w = conf_w.get_contigs(source, conf_w.variant_set_id)
    S_w = driver_w.get_similarity_device_gen(contigs_w)
    driver_w.compute_pca(S_w)
    compile_seconds = time.perf_counter() - warm_start

    # The measured run, ingest-inclusive.
    run_args = [
        _autosome_references() if a == "AUTOSOMES" else a
        for a in config["args"]
    ]
    conf, driver = _make_driver(base_args + run_args, source)
    contigs = conf.get_contigs(source, conf.variant_set_id)
    start = time.perf_counter()
    S = driver.get_similarity_device_gen(contigs)
    result = driver.compute_pca(S)  # fetches the (N, num_pc) components
    wall = time.perf_counter() - start

    # Per-config numbers come from the run MANIFEST (obs/manifest.py) — the
    # same schema-validated document ``--metrics-json`` writes — not from
    # driver internals: what this benchmark reports is what any operator's
    # manifest would say.
    from spark_examples_tpu.obs.manifest import (
        build_run_manifest,
        manifest_metric_value,
        validate_manifest,
    )
    from spark_examples_tpu.obs.metrics import (
        DEVICEGEN_DISPATCHES,
        DEVICEGEN_SITES_CAPACITY,
        GRAMIAN_RING_BYTES,
        INGEST_SITES_SCANNED,
    )

    manifest = build_run_manifest(
        conf=conf,
        spans=driver.spans,
        registry=driver.registry,
        io_stats=driver.io_stats,
    )
    schema_errors = validate_manifest(manifest)
    assert not schema_errors, schema_errors
    acc = driver._device_gen_acc

    def metric(name):
        value = manifest_metric_value(manifest, name)
        assert value is not None, f"manifest missing metric {name!r}"
        return int(value)

    sites_scanned = metric(INGEST_SITES_SCANNED)
    variant_rows = int(manifest["io_stats"]["variants"])
    dispatches = metric(DEVICEGEN_DISPATCHES)
    assert len(result) == total_columns
    assert all(len(pcs) == 2 for _, pcs in result)

    # Dispatch padding waste: grid capacity dispatched (tail-group padding
    # included) vs the valid sites inside it — the fixed small-run overhead
    # that puts brca1 ~3 orders of magnitude below whole-genome throughput.
    sites_capacity = metric(DEVICEGEN_SITES_CAPACITY)
    padding_waste = (
        round(1.0 - sites_scanned / sites_capacity, 4) if sites_capacity else 0.0
    )
    # Ring-exchange ICI traffic (sharded configs only): straight from the
    # manifest counter, so packed-vs-unpacked is visible per artifact.
    ring_bytes = manifest_metric_value(manifest, GRAMIAN_RING_BYTES)

    # Predicted-vs-measured ring bytes from the manifest's schedule block
    # (sharded runs): the STATIC per-flush projection next to the
    # per-flush-accounted total — a nonzero delta means a counts-fallback
    # flush or formula drift, and BENCH rounds catch it per artifact.
    schedule = manifest.get("schedule") or {}
    sched_predicted = schedule.get("predicted_ring_bytes")
    sched_measured = schedule.get("measured_ring_bytes")
    sched_delta = (
        round(abs(sched_measured - sched_predicted) / sched_predicted, 6)
        if sched_predicted
        else None
    )

    # Host-memory headroom (manifest schema v2): measured peak RSS next to
    # the static bound parallel/mesh.py:host_peak_bytes proves for bounded
    # ingest paths — BENCH artifacts record how much of the proven budget
    # each config actually used.
    host_memory = manifest.get("host_memory") or {}
    host_peak = host_memory.get("peak_rss_bytes")
    host_bound = host_memory.get("static_bound_bytes")

    # Device ingest parallelizes over the mesh — report throughput per chip
    # actually used: data axis × samples axis (the ring accumulator puts
    # every chip to work on the samples axis even at data_parallel=1).
    chips_used = getattr(acc, "data_parallel", 1) * getattr(
        acc, "samples_parallel", 1
    )
    baseline = config["baseline_seconds"]
    return {
        "metric": (
            f"{config['metric']} (end-to-end incl. ingest; "
            f"{total_columns} columns, {sites_scanned} sites)"
        ),
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(baseline / wall, 2) if baseline else None,
        "details": {
            "sites_scanned": sites_scanned,
            "variant_rows_accumulated": variant_rows,
            "sites_per_sec_per_chip": round(sites_scanned / wall / chips_used),
            "chips_used": chips_used,
            "device_dispatches": dispatches,
            "sites_capacity_dispatched": sites_capacity,
            "dispatch_padding_waste_fraction": padding_waste,
            **(
                {"gramian_ring_bytes": int(ring_bytes)}
                if ring_bytes is not None
                else {}
            ),
            **(
                {
                    "reduce_schedule": schedule.get("kind"),
                    "sched_predicted_bytes": int(sched_predicted),
                    "sched_ring_bytes_delta_fraction": sched_delta,
                }
                if sched_predicted is not None
                else {}
            ),
            **(
                {"host_peak_rss_bytes": int(host_peak)}
                if host_peak is not None
                else {}
            ),
            **(
                {
                    "host_static_bound_bytes": int(host_bound),
                    "host_mem_headroom_fraction": (
                        round(1.0 - host_peak / host_bound, 4)
                        if host_peak is not None and host_bound
                        else None
                    ),
                }
                if host_bound is not None
                else {}
            ),
            # Prover-conformance pairs straight from the manifest block
            # (measured vs proven per prover) — BENCH artifacts carry the
            # regression tripwire verdicts next to the numbers they bound.
            **(
                {"prover_conformance": manifest["conformance"]}
                if manifest.get("conformance")
                else {}
            ),
            "block_size": BLOCK,
            "blocks_per_dispatch": k_resolved,
            "compile_seconds_excluded": round(compile_seconds, 3),
            "gramian_dtype": str(np.dtype("int32")),
            "device": str(device),
            "baseline": (
                "~7200 s on 40 CPU cores (reference README.md:126-138)"
                if baseline
                else "no published reference number for this config"
            ),
        },
    }


def _cache_entries() -> int:
    """Entries in the persistent compile cache (cold vs warm attribution).
    Reads the jax config value ``enable_persistent_compile_cache`` sets
    (``utils/cache.py``)."""
    import os

    try:
        import jax

        directory = jax.config.jax_compilation_cache_dir
        return len(os.listdir(directory)) if directory else 0
    except Exception:
        return 0


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--config",
        choices=sorted(CONFIGS)
        + ["ingest", "serve-load", "multihost"]
        + sorted(ANALYSIS_CONFIGS),
        default=None,
        help=(
            "Run ONE benchmark config (PCA device configs, 'ingest', "
            "'serve-load', 'multihost', or an analyses/ config: grm, "
            "ld-prune, assoc-scan). Default: run ALL "
            "configs and print the whole-genome headline with every "
            "config's result embedded in details.configs — each README "
            "number gets a driver-verified artifact."
        ),
    )
    args = parser.parse_args()

    import jax

    from spark_examples_tpu.utils.cache import enable_persistent_compile_cache

    # Persistent compilation cache outside the repo (shared with the CLI).
    enable_persistent_compile_cache()
    device = jax.devices()[0]

    if args.config is not None:
        with contextlib.redirect_stdout(sys.stderr):
            if args.config == "ingest":
                payload = _run_ingest_config(device)
            elif args.config == "serve-load":
                payload = _run_serve_load_config(device)
            elif args.config == "multihost":
                payload = _run_multihost_config(device)
            elif args.config in ANALYSIS_CONFIGS:
                payload = _run_analysis_config(args.config, device)
            else:
                payload = _run_config(args.config, device)
        print(json.dumps(payload))
        return

    # All configs, one process: later configs reuse live jit caches where
    # shapes repeat; per-config compile_seconds_excluded and the persistent
    # cache entry counts attribute warm vs cold compilation.
    entries_before = _cache_entries()
    results = {}
    with contextlib.redirect_stdout(sys.stderr):
        for name in CONFIGS:
            results[name] = _run_config(name, device)
    headline = results["whole-genome"]
    payload = dict(headline)
    payload["details"] = dict(headline["details"])
    payload["details"]["compile_cache"] = {
        "entries_before": entries_before,
        "entries_after": _cache_entries(),
        "cold_run": entries_before == 0,
    }
    payload["details"]["configs"] = {
        name: {
            "metric": r["metric"],
            "value": r["value"],
            "unit": r["unit"],
            "vs_baseline": r["vs_baseline"],
            # .get: a skipped config (e.g. sharded ring on one device)
            # reports only its skip reason.
            "sites_scanned": r["details"].get("sites_scanned"),
            "sites_per_sec_per_chip": r["details"].get("sites_per_sec_per_chip"),
            "compile_seconds_excluded": r["details"].get(
                "compile_seconds_excluded"
            ),
            "dispatch_padding_waste_fraction": r["details"].get(
                "dispatch_padding_waste_fraction"
            ),
            **(
                {"gramian_ring_bytes": r["details"]["gramian_ring_bytes"]}
                if "gramian_ring_bytes" in r["details"]
                else {}
            ),
            **(
                {
                    "sched_predicted_bytes": r["details"][
                        "sched_predicted_bytes"
                    ],
                    "sched_ring_bytes_delta_fraction": r["details"][
                        "sched_ring_bytes_delta_fraction"
                    ],
                }
                if "sched_predicted_bytes" in r["details"]
                else {}
            ),
            **(
                {"skipped": r["details"]["skipped"]}
                if "skipped" in r["details"]
                else {}
            ),
        }
        for name, r in results.items()
    }
    # The host-side file-ingest data plane rides along: parse scaling by
    # worker count + ingest/compute overlap (see _run_ingest_config).
    with contextlib.redirect_stdout(sys.stderr):
        ingest = _run_ingest_config(device)
    payload["details"]["configs"]["ingest"] = {
        "metric": ingest["metric"],
        "value": ingest["value"],
        "unit": ingest["unit"],
        "vs_baseline": ingest["vs_baseline"],
        "parse_by_workers": ingest["details"]["parse_by_workers"],
        "ingest_compute_overlap": ingest["details"]["ingest_compute_overlap"],
    }
    # The analyses layer rides along too: one manifest-verified artifact
    # per population-genetics workload (GRM/LD/assoc on the same substrate).
    for name in sorted(ANALYSIS_CONFIGS):
        with contextlib.redirect_stdout(sys.stderr):
            r = _run_analysis_config(name, device)
        payload["details"]["configs"][name] = {
            "metric": r["metric"],
            "value": r["value"],
            "unit": r["unit"],
            "vs_baseline": r["vs_baseline"],
            "analysis": r["details"]["analysis"],
            "sites_per_sec": r["details"]["sites_per_sec"],
        }
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
